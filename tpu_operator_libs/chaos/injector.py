"""ChaosInjector: arms a FaultSchedule against a FakeCluster.

Cluster-side faults (error bursts, watch drops, stale reads, flaps,
crash-loop windows, PDB blocks, lease theft) are installed as scheduled
virtual-clock actions — :meth:`FakeCluster.step` fires them, so the
interleaving with reconciles is owned entirely by the runner's loop and
is reproducible from the seed.

Operator-side faults (``operator-crash``) cannot be cluster actions:
the "process" that must die is the caller. They are exposed through
:class:`CrashFuse` — the runner arms the fuse when a crash event comes
due, and the fuse detonates inside the state machines' durable-write
path (:class:`CrashingStateProvider`), aborting the pass mid-transition
exactly the way a SIGKILL between two apiserver writes would.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Optional

from tpu_operator_libs.chaos.schedule import (
    FAULT_API_BURST,
    FAULT_BAD_REVISION,
    FAULT_CRASHLOOP,
    FAULT_DEGRADATION,
    FAULT_LEADER_LOSS,
    FAULT_NODE_KILL,
    FAULT_NOT_READY_FLAP,
    FAULT_OPERATOR_CRASH,
    FAULT_PDB_BLOCK,
    FAULT_REPLICA_KILL,
    FAULT_STALE_READS,
    FAULT_STATE_CORRUPTION,
    FAULT_WATCH_BREAK,
    FAULT_WATCH_DELAY,
    FaultEvent,
    FaultSchedule,
)
from tpu_operator_libs.fsck.registry import SCHEMA_WRAPPER_RE
from tpu_operator_libs.health.precursor import SIGNALS, NodeHealthSignal
from tpu_operator_libs.consts import POD_CONTROLLER_REVISION_HASH_LABEL
from tpu_operator_libs.consts import (
    FederationKeys,
    RemediationKeys,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.k8s.client import ApiServerError, NotFoundError
from tpu_operator_libs.k8s.fake import FakeCluster
from tpu_operator_libs.k8s.objects import Node
from tpu_operator_libs.upgrade.state_provider import (
    NodeUpgradeStateProvider,
)

logger = logging.getLogger(__name__)

#: Revision hash the bad-revision fault rolls the runtime DaemonSet to.
#: Pods carrying it can never become Ready — the "broken libtpu build"
#: the canary guard exists to contain.
BAD_REVISION_HASH = "bad"


@dataclass(frozen=True)
class CorruptionRecord:
    """One landed state-corruption write, for the gate's post-checks:
    every record must be matched by a janitor repair of the same
    (target, key) at or after ``at``."""

    at: float
    target_kind: str  # "node" | "daemonset"
    target: str
    key: str
    mode: int
    value: str


class OperatorCrash(RuntimeError):
    """The simulated operator process died mid-reconcile.

    Deliberately NOT an ApiServerError/ConflictError/NotFoundError: the
    state machines' per-node transient isolation must not swallow it —
    a crash aborts the whole pass, and the runner rebuilds the managers
    from cluster state alone (the resume-from-labels proof).
    """


class CrashFuse:
    """Shared write-counting detonator for operator-crash faults.

    ``arm(budget, after)`` lets the next ``budget`` durable writes
    commit, then raises :class:`OperatorCrash` on the following one —
    before the commit (``after=False``, the write is lost) or after it
    (``after=True``, the write landed but the process died before
    acting on it). Both windows are the classic crash-consistency
    holes; seeds exercise each. While :attr:`pending` the fuse keeps
    raising on every write, so a crash swallowed by a broad exception
    handler deterministically resurfaces instead of vanishing — a dead
    process stays dead until the runner "restarts" it via
    :meth:`reset`.
    """

    def __init__(self) -> None:
        import threading

        # The budget is decremented from parallel bucket workers when
        # the state manager runs with a worker pool; without the lock
        # two racing writes could both consume the last unit and the
        # crash would never fire.
        self._lock = threading.Lock()
        self._budget: Optional[int] = None
        self._after = False
        self.pending = False
        self.fired_total = 0

    def arm(self, budget: int, after: bool) -> None:
        with self._lock:
            self._budget = max(0, budget)
            self._after = after

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._budget is not None

    def reset(self) -> None:
        """The replacement operator process has started. Clears only the
        ``pending`` flag: an ARMED-but-unfired crash survives restarts
        and leader handovers — the schedule says the process dies around
        its time, and whichever incarnation is alive then dies."""
        with self._lock:
            self.pending = False

    def guard(self, write: Callable[[], object]) -> object:
        """Run one durable write under the fuse. The detonation decision
        is atomic; the write itself runs outside the lock so concurrent
        writers (the parallel bucket pool) are not serialized — writes
        already in flight when the fuse blows still land, exactly like
        requests racing a real process death."""
        with self._lock:
            if self.pending:
                raise OperatorCrash("operator process is down (crash "
                                    "pending restart)")
            if self._budget is None:
                detonate = None
            elif self._budget > 0:
                self._budget -= 1
                detonate = None
            else:
                self._budget = None
                self.pending = True
                self.fired_total += 1
                detonate = "after" if self._after else "before"
        if detonate is None:
            return write()
        if detonate == "after":
            write()
            raise OperatorCrash(
                "operator crashed AFTER committing a durable write")
        raise OperatorCrash(
            "operator crashed BEFORE committing a durable write")


class CrashingStateProvider(NodeUpgradeStateProvider):
    """NodeUpgradeStateProvider whose durable writes pass through a
    :class:`CrashFuse`. This is the crash seam: every label/annotation
    commit of both state machines funnels through the provider, so a
    detonation here is indistinguishable from the operator dying between
    (or during) apiserver writes."""

    def __init__(self, *args: object, fuse: CrashFuse,
                 **kwargs: object) -> None:
        super().__init__(*args, **kwargs)  # type: ignore[arg-type]
        self._fuse = fuse

    def change_node_upgrade_state(
            self, node: Node, new_state: "UpgradeState | str",
            annotations: "Optional[dict[str, Optional[str]]]" = None,
    ) -> bool:
        return bool(self._fuse.guard(
            lambda: super(CrashingStateProvider, self)
            .change_node_upgrade_state(node, new_state,
                                       annotations=annotations)))

    def change_node_upgrade_annotation(self, node: Node, key: str,
                                       value: Optional[str]) -> None:
        self._fuse.guard(
            lambda: super(CrashingStateProvider, self)
            .change_node_upgrade_annotation(node, key, value))

    def change_node_upgrade_annotations(
            self, node: Node,
            annotations: "dict[str, Optional[str]]") -> None:
        self._fuse.guard(
            lambda: super(CrashingStateProvider, self)
            .change_node_upgrade_annotations(node, annotations))


class ChaosInjector:
    """Installs a schedule's cluster-side faults; owns the crash fuse.

    ``lease`` identifies the leader-election Lease that leader-loss
    events overwrite. Workload-namespace evictions are the PDB-block
    target (runtime DaemonSet pods are never evicted by drains anyway).
    """

    def __init__(self, cluster: FakeCluster, schedule: FaultSchedule,
                 lease_namespace: str = "kube-system",
                 lease_name: str = "chaos-operator-leader",
                 shard_lease_prefix: str = "",
                 upgrade_keys: Optional[UpgradeKeys] = None,
                 remediation_keys: Optional[RemediationKeys] = None,
                 federation_keys: Optional[FederationKeys] = None) -> None:
        self._cluster = cluster
        self._schedule = schedule
        # key families the state-corruption fault vandalizes (defaults
        # match the fleet builders' driver/domain)
        self._upgrade_keys = upgrade_keys or UpgradeKeys()
        self._remediation_keys = remediation_keys or RemediationKeys()
        self._federation_keys = federation_keys or FederationKeys()
        self._lease_namespace = lease_namespace
        self._lease_name = lease_name
        # sharded-control-plane runs: leader-loss events targeting
        # "shard:<i>" steal the i-th shard Lease of this prefix
        self._shard_lease_prefix = shard_lease_prefix
        self.fuse = CrashFuse()
        self._crash_events: list[FaultEvent] = sorted(
            schedule.by_kind(FAULT_OPERATOR_CRASH), key=lambda e: e.at)
        self._crash_index = 0
        # replica kills are operator-side faults like crashes: the
        # "process" that dies is a caller-owned replica, so the runner
        # polls due events instead of the cluster firing them
        self._replica_kill_events: list[FaultEvent] = sorted(
            schedule.by_kind(FAULT_REPLICA_KILL), key=lambda e: e.at)
        self._replica_kill_index = 0
        self.replicas_killed = 0
        # active crash-loop windows: node -> heal time
        self._crashloop_until: dict[str, float] = {}
        # active PDB windows (static list; the blocker checks the clock)
        self._pdb_windows: list[tuple[float, float]] = []
        self.installed = False
        self.leader_losses = 0
        self.bad_revisions_rolled = 0
        self.nodes_killed = 0
        self.killed_nodes: list[str] = []
        # hardware-health counters the degradation fault ramps; the
        # runner hands ``health_source`` to the remediation manager as
        # its PrecursorSource. Signals exist only for targeted nodes —
        # the precursor model treats an absent node as "no sample",
        # exactly like a telemetry agent that never reported.
        self.health_signals: dict[str, NodeHealthSignal] = {}
        self.degradation_ticks = 0
        #: Every state-corruption write that landed (the fsck gate's
        #: repair-coverage ledger).
        self.corruptions: list[CorruptionRecord] = []

    # -- installation -----------------------------------------------------
    def install(self) -> None:
        """Arm every cluster-side fault as a scheduled virtual action."""
        if self.installed:
            return
        self.installed = True
        cluster = self._cluster
        for event in self._schedule.events:
            if event.kind == FAULT_API_BURST:
                cluster.schedule_at(
                    event.at, lambda e=event: cluster.inject_api_errors(
                        e.target, e.param))
            elif event.kind == FAULT_WATCH_BREAK:
                cluster.schedule_at(
                    event.at, lambda: cluster.drop_watch_streams())
            elif event.kind == FAULT_WATCH_DELAY:
                # schedules its own start/flush actions; seed-pure in
                # the event's param
                cluster.delay_watch_events(event.at, event.until,
                                           seed=event.param)
            elif event.kind == FAULT_STALE_READS:
                cluster.schedule_at(
                    event.at, lambda e=event: self._inject_stale(e))
            elif event.kind == FAULT_NOT_READY_FLAP:
                cluster.flap_node_ready(event.target, event.at,
                                        event.until)
            elif event.kind == FAULT_CRASHLOOP:
                cluster.schedule_at(
                    event.at,
                    lambda e=event: self._crashloop_until.__setitem__(
                        e.target, e.until))
            elif event.kind == FAULT_PDB_BLOCK:
                self._pdb_windows.append((event.at, event.until))
            elif event.kind == FAULT_LEADER_LOSS:
                cluster.schedule_at(
                    event.at, lambda e=event: self._steal_lease(e))
            elif event.kind == FAULT_BAD_REVISION:
                cluster.schedule_at(
                    event.at,
                    lambda e=event: self._inject_bad_revision(e))
            elif event.kind == FAULT_NODE_KILL:
                cluster.schedule_at(
                    event.at, lambda e=event: self._kill_node(e))
            elif event.kind == FAULT_DEGRADATION:
                self._install_degradation(event)
            elif event.kind == FAULT_STATE_CORRUPTION:
                cluster.schedule_at(
                    event.at, lambda e=event: self._corrupt(e))
        if any(e.kind == FAULT_NODE_KILL for e in self._schedule.events):
            # a dead host's kubelet never reports a healthy container:
            # pods recreated on a killed node crash-loop until the node
            # is Ready again (it never is — kills do not heal)
            cluster.gate_pod_ready_on_node_ready()
        if any(e.kind == FAULT_CRASHLOOP for e in self._schedule.events):
            cluster.add_pod_ready_gate(self._ready_gate)
        if any(e.kind == FAULT_BAD_REVISION
               for e in self._schedule.events):
            # the broken build: any pod recreated from the bad revision
            # crash-loops forever — there is no heal window; recovery is
            # the canary guard's rollback or nothing
            cluster.add_pod_ready_gate(
                lambda pod: pod.metadata.labels.get(
                    POD_CONTROLLER_REVISION_HASH_LABEL)
                != BAD_REVISION_HASH)
        if self._pdb_windows:
            cluster.add_eviction_blocker(self._eviction_blocked)

    def _inject_bad_revision(self, event: FaultEvent) -> None:
        namespace, _, name = event.target.partition("/")
        self.bad_revisions_rolled += 1
        logger.info("chaos: rolling DaemonSet %s to broken revision %r",
                    event.target, BAD_REVISION_HASH)
        self._cluster.bump_daemon_set_revision(namespace, name,
                                               BAD_REVISION_HASH)

    # -- state corruption -------------------------------------------------
    def _corrupt(self, event: FaultEvent) -> None:
        """Vandalize one durable stamp the way an external writer would.

        Writes go through the RAW cluster (ride-out on injected API
        faults via :func:`consume_transient`), never the crash fuse:
        corruption is not the operator's write, so it neither consumes
        the fuse budget nor respects the provider's preconditions. Every
        landed write is recorded in :attr:`corruptions` so the fsck gate
        can demand a matching janitor repair. Values are chosen so the
        auditor provably classifies each one (garbage validators fail,
        ghost incumbents never exist, wrappers always read as skew) —
        a corruption the auditor could mistake for legitimate state
        would make the repair-coverage check vacuous.
        """
        up = self._upgrade_keys
        rem = self._remediation_keys
        fed = self._federation_keys
        mode = event.param % 6
        variant = event.param // 6
        cluster = self._cluster
        node = event.target

        if mode == 0:
            # garbage value on a registered node annotation; every
            # payload has ZERO codec-decodable survivors, so normalize
            # repairs delete rather than partially restore
            key, value = (
                (up.validation_start_annotation, "not-a-number"),
                (up.phase_durations_annotation, "drain=abc,bogus"),
                (rem.precursor_rates_annotation, "ecc=??,zzz=1"),
                (up.phase_start_annotation, "warp:xx"),
            )[variant % 4]
            self._write_node_annotation(event, node, key, value, mode)
        elif mode == 1:
            # orphaned prewarm stamp naming a GHOST incumbent — provably
            # dead regardless of fleet state; the ready variant is also
            # a torn pair (join stamp without its reserve half)
            if variant % 2 == 0:
                key, value = (up.prewarm_reservation_annotation,
                              "ghost-host:m1:gold")
            else:
                key, value = (up.prewarm_ready_annotation,
                              "ghost-host:123.0")
            self._write_node_annotation(event, node, key, value, mode)
        elif mode == 2:
            # garbage shard-owner label (labels, not annotations: the
            # other repair path)
            key, value = up.shard_label, "shard-!!"
            consume_transient(lambda: cluster.patch_node_labels(
                node, {key: value}))
            self.corruptions.append(CorruptionRecord(
                at=event.at, target_kind="node", target=node, key=key,
                mode=mode, value=value))
        elif mode == 3:
            # cross-subsystem collision: an unregistered key squatting
            # under the owned prefix
            key = f"{up.domain}/{up.driver}-upgrade.bogus-{variant}"
            self._write_node_annotation(event, node, key, "1", mode)
        elif mode == 4:
            # schema-version skew: wrap a PRESENT stamp so the convert
            # repair must restore the exact original — never fabricate
            # a value that was not there
            live = consume_transient(lambda: cluster.get_node(node))
            key, value = up.phase_durations_annotation, "v0;bogus"
            for candidate in (up.phase_durations_annotation,
                              rem.precursor_rates_annotation,
                              up.phase_start_annotation):
                current = live.metadata.annotations.get(candidate, "")
                if current and not SCHEMA_WRAPPER_RE.match(current):
                    key, value = candidate, f"v0;{current}"
                    break
            self._write_node_annotation(event, node, key, value, mode)
        else:
            # DaemonSet stamp corruption (dangling shard attestation /
            # garbled federation ledger entries)
            namespace, _, name = event.target.partition("/")
            key, value = (
                (up.canary_shard_passed_prefix + "99", "deadbeef"),
                (fed.budget_share_annotation, "not-an-int"),
                (fed.bake_passed_annotation, "garbled"),
            )[variant % 3]
            consume_transient(
                lambda: cluster.patch_daemon_set_annotations(
                    namespace, name, {key: value}))
            self.corruptions.append(CorruptionRecord(
                at=event.at, target_kind="daemonset", target=event.target,
                key=key, mode=mode, value=value))
        logger.info("chaos: corrupted %s (mode %d) on %s", key, mode,
                    event.target)

    def _write_node_annotation(self, event: FaultEvent, node: str,
                               key: str, value: str, mode: int) -> None:
        consume_transient(lambda: self._cluster.patch_node_annotations(
            node, {key: value}))
        self.corruptions.append(CorruptionRecord(
            at=event.at, target_kind="node", target=node, key=key,
            mode=mode, value=value))

    def _install_degradation(self, event: FaultEvent) -> None:
        """Arm one degradation ramp as a fixed cadence of counter
        bumps across ``[at, until)``. Everything is derived from the
        event alone (seed-pure): ``param`` picks the signal family
        (``param %% len(SIGNALS)``) and the per-tick increment, and the
        tick times are evenly spaced — no RNG at injection time, so
        the same schedule always ramps the same counters to the same
        values at the same virtual instants."""
        signal = SIGNALS[event.param % len(SIGNALS)]
        by = max(1, event.param)
        window = max(1.0, event.until - event.at)
        ticks = 12
        for i in range(ticks):
            at = event.at + window * i / ticks
            self._cluster.schedule_at(
                at, lambda e=event, s=signal, b=by: self._degrade(
                    e.target, s, b))

    def _degrade(self, node: str, signal: str, by: int) -> None:
        sig = self.health_signals.get(node)
        if sig is None:
            sig = self.health_signals[node] = NodeHealthSignal(node)
        sig.bump(signal, by)
        self.degradation_ticks += 1

    def health_source(self) -> "dict[str, dict[str, int]]":
        """Snapshot every ramped node's counters — the PrecursorSource
        the runner hands to the remediation manager. Non-ramped nodes
        are absent (no telemetry ever reported), which the model treats
        as "no sample this pass"."""
        return {name: dict(sig.read())
                for name, sig in self.health_signals.items()}

    def _kill_node(self, event: FaultEvent) -> None:
        """Permanent NotReady: the node is dead hardware. No heal is
        ever scheduled — remediation must condemn it and the
        reconfigurer must route its slice around it."""
        self.nodes_killed += 1
        self.killed_nodes.append(event.target)
        logger.info("chaos: killing node %s (permanent NotReady)",
                    event.target)
        self._cluster.set_node_ready(event.target, False)

    def _inject_stale(self, event: FaultEvent) -> None:
        try:
            self._cluster.inject_stale_node_reads(event.target, event.param)
        except NotFoundError:
            # the target node vanished before the fault fired — a chaos
            # run must not die on its own injection
            logger.info("stale-read target %s gone; skipping", event.target)

    def _ready_gate(self, pod) -> bool:
        heal = self._crashloop_until.get(pod.spec.node_name)
        return heal is None or self._cluster.clock.now() >= heal

    def _eviction_blocked(self, pod) -> bool:
        now = self._cluster.clock.now()
        if not any(start <= now < end for start, end in self._pdb_windows):
            return False
        # PDB semantics: budgets guard workload pods; DaemonSet-owned
        # runtime pods are deleted (not evicted) and drains skip them
        owner = pod.controller_owner()
        return owner is None or owner.kind != "DaemonSet"

    def _steal_lease(self, event: Optional[FaultEvent] = None) -> None:
        self.leader_losses += 1
        name = self._lease_name
        target = event.target if event is not None else ""
        if target.startswith("shard:") and self._shard_lease_prefix:
            # sharded control plane: depose one SHARD's owner — the
            # incumbent's fencing check must reject its queued writes
            # and the preferred replica re-adopts after expiry
            name = (f"{self._shard_lease_prefix}-shard-"
                    f"{int(target.split(':', 1)[1]):02d}")
        self._cluster.steal_lease(
            self._lease_namespace, name,
            f"chaos-intruder-{self.leader_losses}")

    # -- operator-side faults ---------------------------------------------
    def arm_due_crashes(self, now: float) -> bool:
        """Arm the fuse for any crash event at or before ``now`` not yet
        armed. Returns True when one was armed this call."""
        armed = False
        while (self._crash_index < len(self._crash_events)
               and self._crash_events[self._crash_index].at <= now):
            event = self._crash_events[self._crash_index]
            self._crash_index += 1
            # parity of the write budget decides the crash window:
            # before vs after the durable commit
            self.fuse.arm(event.param, after=event.param % 2 == 1)
            armed = True
        return armed

    def due_replica_kills(self, now: float) -> "list[FaultEvent]":
        """Consume (once) every replica-kill event at or before ``now``.
        The runner owns the replica objects, so it applies the kill —
        dropping the incarnation WITHOUT releasing its Leases — and
        schedules the replacement at the event's ``until``."""
        due: list[FaultEvent] = []
        while (self._replica_kill_index < len(self._replica_kill_events)
               and self._replica_kill_events[
                   self._replica_kill_index].at <= now):
            event = self._replica_kill_events[self._replica_kill_index]
            self._replica_kill_index += 1
            self.replicas_killed += 1
            due.append(event)
        return due

    @property
    def crashes_fired(self) -> int:
        return self.fuse.fired_total


def consume_transient(fn: Callable[[], object],
                      attempts: int = 12) -> object:
    """Run harness-side bookkeeping reads through injected API faults.

    The injector deliberately poisons shared client operations; the
    HARNESS (monitor resyncs, convergence checks, workload restore) must
    ride those out the way any other client would — retry, consuming the
    injected budget — without mistaking its own tooling for the system
    under test."""
    last: Optional[Exception] = None
    for _ in range(attempts):
        try:
            return fn()
        except (ApiServerError, TimeoutError) as exc:
            last = exc
    raise RuntimeError(
        f"injected fault budget not consumable in {attempts} attempts"
    ) from last
