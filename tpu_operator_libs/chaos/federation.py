"""The multi-cluster federation chaos gate.

A :func:`run_federation_soak` episode builds N REGIONS — each a real
:class:`~tpu_operator_libs.k8s.fake.FakeCluster` running a real
:class:`~tpu_operator_libs.upgrade.state_manager.
ClusterUpgradeStateManager` incarnation, all sharing one virtual clock
— and a :class:`~tpu_operator_libs.federation.controller.
FederationController` driving them through a global rollout, while the
seed's schedule kills regional controllers mid-rollout, partitions the
federation from regions (stale reads + rejected writes), and kills the
federation controller itself mid-wave. A :class:`FederationMonitor`
reads every cluster directly — below the ledger layer, below the
controller under test — and holds three always-on invariants:

- **global-budget**: the SUM of observed per-region unavailability
  never exceeds the global ``B``, at any sampled instant, across
  kills, partitions and controller replacements — the durable share
  stamps coordinate the joint spend with no live coordinator required;
- **canary-containment**: no non-canary region's DaemonSet ever moves
  to a revision lacking the fleet bake-passed stamp (bake elapsed) or
  carrying a quarantine verdict, and no pod of a quarantined revision
  ever exists outside the canary region;
- **federation-resume**: controllers rebuilt with zero in-memory state
  converge the rollout from the regions' durable annotations alone,
  and the end state carries no share residue (every stamp back to 0)
  and no pre-shift stamp residue (the reservation→ready pairs all
  released; the fsck registry's torn-pair audit agrees);
- **session-zero-drop**: a fixed population of interactive sessions
  per region (capacity = serving nodes × ``sessions_per_node``) never
  drops a session across region admissions — every capacity deficit a
  rollout opens is absorbed by a READY cross-region pre-shift
  reservation, sampled from ground truth below the gateways.

The federation reads the regions watch-driven by default
(``watch_regions``): the schedule's watch-delay windows buffer event
delivery (the region's change cursor must go stale and freeze raises
rather than trust a frozen cache) and its watch-break stops the
federation's region streams mid-bake (repair = a relist of that
region only, through the Informer rewatch machinery).

:func:`run_federation_bad_revision_soak` is the containment flavor:
the federation's target becomes a revision whose pods can never become
Ready — the canary region's own RolloutGuard must halt and roll back
locally, the federation must lift the quarantine fleet-wide, and no
non-canary region may ever admit the condemned hash, with the same
fault storm landing on the machinery that proves it.
"""

from __future__ import annotations

import copy
import logging
import math
from dataclasses import dataclass
from typing import Optional

from tpu_operator_libs.api.federation_policy import FederationPolicySpec
from tpu_operator_libs.api.upgrade_policy import (
    CanaryRolloutSpec,
    DrainSpec,
    RollbackSpec,
    UpgradePolicySpec,
    scaled_value_from_int_or_percent,
)
from tpu_operator_libs.chaos.injector import (
    BAD_REVISION_HASH,
    CrashFuse,
    CrashingStateProvider,
    OperatorCrash,
    consume_transient,
)
from tpu_operator_libs.chaos.invariants import InvariantViolation
from tpu_operator_libs.chaos.runner import ChaosReport
from tpu_operator_libs.chaos.schedule import (
    FAULT_API_BURST,
    FAULT_BAD_REVISION,
    FAULT_FED_KILL,
    FAULT_FED_PARTITION,
    FAULT_OPERATOR_CRASH,
    FAULT_REGION_KILL,
    FAULT_WATCH_BREAK,
    FAULT_WATCH_DELAY,
    FaultSchedule,
)
from tpu_operator_libs.consts import (
    POD_CONTROLLER_REVISION_HASH_LABEL,
    FederationKeys,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.federation import (
    FederationBudgetLedger,
    FederationController,
    RegionHandle,
)
from tpu_operator_libs.fsck.auditor import StateAuditor
from tpu_operator_libs.fsck.registry import default_registry
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    NotFoundError,
)
from tpu_operator_libs.k8s.fake import FakeCluster
from tpu_operator_libs.simulate import (
    NS,
    RUNTIME_LABELS,
    FleetSpec,
    build_fleet,
)
from tpu_operator_libs.upgrade.state_manager import (
    BuildStateError,
    ClusterUpgradeStateManager,
)
from tpu_operator_libs.util import FakeClock

logger = logging.getLogger(__name__)

#: Revision the good-path episode rolls the fleet to first.
FED_TARGET_REVISION = "new"
#: Second target, promoted at horizon/2 (the other gates' idiom):
#: guarantees write traffic deep into the fault window, so every armed
#: operator crash detonates, and lands the late kills on a mid-wave
#: fleet. Convergence is judged against THIS revision.
FED_FINAL_REVISION = "new2"


@dataclass
class FederationChaosConfig:
    """Knobs of one federation soak episode (defaults: tier-1 shape)."""

    regions: tuple = ("asia", "europe", "uswest")
    n_slices: int = 2
    hosts_per_slice: int = 2
    pod_recreate_delay: float = 2.0
    pod_ready_delay: float = 6.0
    reconcile_interval: float = 10.0
    horizon: float = 600.0
    max_steps: int = 400
    #: Global disruption budget across ALL regions combined.
    global_max_unavailable: str = "50%"
    #: Fleet bake after the canary REGION converges.
    bake_seconds: int = 30
    #: Node-level canary bake INSIDE each region (the per-cluster
    #: guard runs live — it is the verdict machine the federation
    #: lifts fleet-wide).
    region_bake_seconds: int = 10
    max_concurrent_regions: int = 1
    follow_the_sun: bool = True
    trough_utilization: float = 0.45
    max_trough_wait_seconds: int = 480
    #: When set, pods of this revision hash can never become Ready in
    #: ANY region (the fleet-promoted broken build of the containment
    #: gate; installed as a pod-ready gate at region build time).
    bad_revision: str = ""
    #: Diurnal utilization model per region: phase-offset sinusoids,
    #: so each region troughs in its own window (follow-the-sun).
    diurnal_period: float = 240.0
    util_base: float = 0.55
    util_amplitude: float = 0.35
    #: Watch-driven federation reads (region_watch.py). False drops
    #: back to the polled read path — the bench's baseline arm.
    watch_regions: bool = True
    #: Staleness bound on each region's change cursor (watch mode).
    watch_staleness_seconds: float = 30.0
    #: Cross-region session pre-shift (reservation→ready on the
    #: reserve region's DS before any region admission).
    session_pre_shift: bool = True
    #: Interactive sessions per serving node (sizes each region's
    #: fixed session population AND its live capacity).
    sessions_per_node: int = 2
    #: Virtual seconds a pre-shift reservation takes to become
    #: serving-ready (the readiness hook's warmup model).
    preshift_warmup_seconds: float = 15.0
    #: Bounded pre-shift wait before an audited admit-anyway.
    max_preshift_wait_seconds: int = 480

    @property
    def nodes_per_region(self) -> int:
        return self.n_slices * self.hosts_per_slice

    @property
    def total_nodes(self) -> int:
        return len(self.regions) * self.nodes_per_region

    @property
    def global_budget(self) -> int:
        return scaled_value_from_int_or_percent(
            self.global_max_unavailable, self.total_nodes,
            round_up=True)

    def region_utilization(self, index: int, now: float) -> float:
        """Region ``index``'s live utilization at ``now`` — a pure
        phase-offset sinusoid (config, not seed: the federation's
        follow-the-sun ordering must be reproducible across controller
        restarts within one episode)."""
        phase = 2.0 * math.pi * index / max(1, len(self.regions))
        value = self.util_base + self.util_amplitude * math.sin(
            2.0 * math.pi * now / self.diurnal_period + phase)
        return max(0.0, min(1.0, value))

    def federation_policy(self, canary: str) -> FederationPolicySpec:
        return FederationPolicySpec(
            global_max_unavailable=self.global_max_unavailable,
            canary_region=canary,
            bake_seconds=self.bake_seconds,
            max_concurrent_regions=self.max_concurrent_regions,
            follow_the_sun=self.follow_the_sun,
            trough_utilization=self.trough_utilization,
            max_trough_wait_seconds=self.max_trough_wait_seconds,
            watch_staleness_seconds=self.watch_staleness_seconds,
            session_pre_shift=self.session_pre_shift,
            max_preshift_wait_seconds=self.max_preshift_wait_seconds)


class _FedGateway:
    """The federation's access path to ONE region apiserver, with the
    partition fault in the middle: inside a window, writes are
    rejected (ApiServerError) and reads are served from the
    pre-partition snapshot cache — a stale regional replica. The
    region's OWN operator talks to its cluster directly (the partition
    is federation↔region, not region-internal)."""

    _READS = frozenset((
        "list_daemon_sets", "list_nodes", "list_pods",
        "list_controller_revisions", "get_node"))
    _WRITES = frozenset((
        "patch_daemon_set_annotations", "bump_daemon_set_revision",
        "rollback_daemon_set", "patch_node_labels",
        "patch_node_annotations", "patch_node_meta"))

    def __init__(self, cluster: FakeCluster) -> None:
        self._cluster = cluster
        self._windows: "list[tuple[float, float]]" = []
        self._stale: "dict[tuple, object]" = {}
        #: Calls refused/served-stale inside partition windows (the
        #: harness-sanity proof the partition actually bit).
        self.partitioned_calls = 0
        #: Every watch stream vended through this gateway (the
        #: watch-break fault's blast surface).
        self._watches: "list[_GatedWatch]" = []

    def add_window(self, start: float, end: float) -> None:
        self._windows.append((start, end))

    def partitioned(self) -> bool:
        now = self._cluster.clock.now()
        return any(start <= now < end for start, end in self._windows)

    def watch(self, *args: "object", **kwargs: "object") -> "object":
        """Gated subscription: ``watch`` is not in ``_READS`` (it
        vends a stream, not a snapshot), so it needs this explicit
        seam — otherwise ``__getattr__`` would hand the federation an
        ungated stream that tunnels events straight through a
        partition window."""
        gated = _GatedWatch(self, self._cluster.watch(*args, **kwargs))
        self._watches.append(gated)
        return gated

    def drop_streams(self) -> int:
        """Watch-break fault, silent flavor: every federation-side
        stream of this region stops with no marker — the consumer
        must infer the gap and relist (Informer rewatch)."""
        dropped = 0
        for gated in self._watches:
            if not gated.stopped:
                gated.stop()
                dropped += 1
        return dropped

    def expire_streams(self) -> int:
        """Watch-break fault, 410 flavor: the server declares the
        cursor expired in-band before stopping each stream."""
        expired = 0
        for gated in self._watches:
            if not gated.stopped:
                gated.expire()
                expired += 1
        return expired

    def __getattr__(self, name: str) -> "object":
        if name in self._WRITES:
            real = getattr(self._cluster, name)

            def write(*args: "object", **kwargs: "object") -> "object":
                if self.partitioned():
                    self.partitioned_calls += 1
                    raise ApiServerError(
                        f"federation partitioned from region "
                        f"({name} rejected)")
                return real(*args, **kwargs)
            return write
        if name in self._READS:
            real = getattr(self._cluster, name)

            def read(*args: "object", **kwargs: "object") -> "object":
                key = (name, repr(args), repr(sorted(kwargs.items())))
                if self.partitioned():
                    self.partitioned_calls += 1
                    cached = self._stale.get(key)
                    if cached is None:
                        raise ApiServerError(
                            f"federation partitioned from region "
                            f"({name}: no cached read)")
                    return copy.deepcopy(cached)
                result = real(*args, **kwargs)
                self._stale[key] = copy.deepcopy(result)
                return result
            return read
        return getattr(self._cluster, name)


class _GatedWatch:
    """One region watch stream as the federation sees it through the
    partition: inside a window events are WITHHELD (``get`` returns
    None — the stream looks idle, exactly how a cut long-poll reads),
    and the backlog drains the moment the window lifts. Detecting the
    silence is the staleness bound's job, not the stream's."""

    def __init__(self, gateway: _FedGateway, watch: "object") -> None:
        self._gateway = gateway
        self._watch = watch

    def get(self, timeout: "Optional[float]" = None) -> "object":
        if self._gateway.partitioned():
            self._gateway.partitioned_calls += 1
            return None
        return self._watch.get(timeout=timeout)

    @property
    def stopped(self) -> bool:
        return self._watch.stopped

    def stop(self) -> None:
        self._watch.stop()

    def expire(self) -> None:
        self._watch.expire()


class _RegionOperator:
    """One regional controller process-lifetime (fresh manager, fresh
    provider; everything durable lives in the region's cluster)."""

    def __init__(self, cluster: FakeCluster, clock: FakeClock,
                 keys: UpgradeKeys, fuse: CrashFuse,
                 identity: str) -> None:
        self.identity = identity
        provider = CrashingStateProvider(
            cluster, keys, None, clock, sync_timeout=5.0,
            poll_interval=1.0, fuse=fuse)
        self.upgrade = ClusterUpgradeStateManager(
            cluster, keys, clock=clock, async_workers=False,
            provider=provider, poll_interval=1.0, sync_timeout=5.0)


@dataclass
class _Region:
    name: str
    index: int
    cluster: FakeCluster
    gateway: _FedGateway
    op: "Optional[_RegionOperator]" = None
    generation: int = 1


class _SessionFleet:
    """A fixed population of interactive sessions per region, routed
    from ground truth BELOW the gateways (like the monitor). Capacity
    is serving nodes × ``sessions_per_node``; every tick, a region's
    capacity deficit is absorbed by a READY pre-shift reservation
    naming it as source (capacity the federation reserved in an
    adjacent region before admitting this one) and anything left over
    DROPS — the zero-drop invariant's direct evidence. The model is
    deliberately worst-case: sessions never shrink, shed, or retry."""

    def __init__(self, sim: "FederationFleetSim") -> None:
        self.sim = sim
        per_region = (sim.config.nodes_per_region
                      * sim.config.sessions_per_node)
        self.population = {name: per_region for name in sim.regions}
        self.drops_total = 0
        #: ticks where at least one session rode a pre-shift reserve
        #: (harness sanity: the invariant must have been exercised).
        self.shift_ticks = 0
        self.max_shifted = 0
        self.drop_events: "list[tuple[float, str, int]]" = []

    def sessions(self, region: str) -> int:
        return self.population[region]

    def tick(self) -> None:
        sim = self.sim
        spn = sim.config.sessions_per_node
        res_key = sim.fed_keys.preshift_reservation_annotation
        ready_key = sim.fed_keys.preshift_ready_annotation
        ready_slots: "dict[str, int]" = {}
        for region in sim.regions.values():
            daemon_sets = consume_transient(
                lambda c=region.cluster: c.list_daemon_sets(NS))
            ds = next((d for d in daemon_sets
                       if d.metadata.name == "libtpu"), None)
            if ds is None:
                continue
            reservation = FederationController._parse_reservation(
                ds.metadata.annotations.get(res_key, ""))
            ready = FederationController._parse_ready(
                ds.metadata.annotations.get(ready_key, ""))
            # only a COMPLETE pair serves traffic: a reservation whose
            # ready stamp has not landed is capacity on paper
            if reservation is not None and ready is not None \
                    and ready[0] == reservation[0] \
                    and ready[1] == reservation[1]:
                source = reservation[0]
                ready_slots[source] = (ready_slots.get(source, 0)
                                       + reservation[2])
        for name, region in sorted(sim.regions.items()):
            nodes = consume_transient(region.cluster.list_nodes)
            capacity = spn * sum(
                1 for node in nodes
                if node.is_ready() and not node.is_unschedulable())
            deficit = self.population[name] - capacity
            if deficit <= 0:
                continue
            absorbed = min(deficit, ready_slots.get(name, 0))
            if absorbed > 0:
                self.shift_ticks += 1
                self.max_shifted = max(self.max_shifted, absorbed)
            dropped = deficit - absorbed
            if dropped > 0:
                self.drops_total += dropped
                self.drop_events.append(
                    (sim.clock.now(), name, dropped))


class FederationFleetSim:
    """N simulated regions + the federation controller above them.

    Shared by the chaos runners and ``tools/federation_bench.py``: the
    bench drives it fault-free for the makespan/latency numbers, the
    soaks layer the schedule on top.
    """

    def __init__(self, config: FederationChaosConfig,
                 clock: Optional[FakeClock] = None) -> None:
        self.config = config
        self.clock = clock if clock is not None else FakeClock(start=0.0)
        self.keys = UpgradeKeys()
        self.fed_keys = FederationKeys()
        self.ledger = FederationBudgetLedger(self.fed_keys)
        self.fuse = CrashFuse()
        #: The canary region is the lowest-utilization region at t=0 —
        #: deterministic from config alone, pinned into the policy so
        #: every federation incarnation agrees mid-episode.
        spec = FleetSpec(
            n_slices=config.n_slices,
            hosts_per_slice=config.hosts_per_slice,
            pod_recreate_delay=config.pod_recreate_delay,
            pod_ready_delay=config.pod_ready_delay)
        self.regions: "dict[str, _Region]" = {}
        for index, name in enumerate(config.regions):
            cluster, _, _ = build_fleet(spec, clock=self.clock,
                                        roll=False)
            if config.bad_revision:
                cluster.add_pod_ready_gate(
                    lambda pod, bad=config.bad_revision:
                    pod.metadata.labels.get(
                        POD_CONTROLLER_REVISION_HASH_LABEL) != bad)
            self.regions[name] = _Region(
                name=name, index=index, cluster=cluster,
                gateway=_FedGateway(cluster))
        self.canary = min(
            self.regions,
            key=lambda name: (config.region_utilization(
                self.regions[name].index, 0.0), name))
        self.fed: Optional[FederationController] = None
        self.fed_generation = 0
        self.region_incarnations = 0
        self.sessions = _SessionFleet(self)
        self.build_fed()
        for name in self.regions:
            self.build_region_op(name)

    # -- construction (also the restart paths) -------------------------
    def build_fed(self) -> FederationController:
        """A FRESH federation controller — zero in-memory state, which
        is exactly what a post-kill replacement has."""
        self.fed_generation += 1
        config = self.config
        handles = []
        for name, region in sorted(self.regions.items()):
            handles.append(RegionHandle(
                name=name, client=region.gateway, namespace=NS,
                ds_name="libtpu",
                utilization=(lambda now, index=region.index:
                             config.region_utilization(index, now)),
                sessions=(lambda name=name:
                          self.sessions.sessions(name)),
                # the readiness model: reserved capacity is serving-
                # ready once the warmup elapsed past the durable
                # reservation epoch — restart-stable, because the
                # epoch lives in the stamp, not in controller memory
                preshift_ready=(
                    lambda slots, reserved_at:
                    self.clock.now() >= reserved_at
                    + config.preshift_warmup_seconds)))
        self.fed = FederationController(
            handles, config.federation_policy(self.canary),
            keys=self.fed_keys, upgrade_keys=self.keys,
            clock=self.clock, watch=config.watch_regions)
        return self.fed

    def build_region_op(self, name: str) -> _RegionOperator:
        region = self.regions[name]
        self.region_incarnations += 1
        region.op = _RegionOperator(
            region.cluster, self.clock, self.keys, self.fuse,
            identity=f"{name}-op-{region.generation}")
        return region.op

    # -- the region policy surface --------------------------------------
    def region_policy(self, name: str) -> UpgradePolicySpec:
        """The policy the region operator consumes, derived from the
        region's OWN durable state: its effective ``maxUnavailable``
        is the federation's share stamp (absent = 0 = admit nothing),
        so the global budget binds region-locally through partitions
        and controller replacements alike."""
        config = self.config
        region = self.regions[name]
        share = 0
        for ds in region.cluster.list_daemon_sets(NS):
            if ds.metadata.name == "libtpu":
                share = self.ledger.share_from(
                    ds.metadata.annotations) or 0
                break
        return UpgradePolicySpec(
            auto_upgrade=True,
            max_parallel_upgrades=0,
            max_unavailable=share,
            topology_mode="flat",
            drain=DrainSpec(enable=True, force=True,
                            timeout_seconds=300),
            canary=CanaryRolloutSpec(
                enable=True, canary_count=1,
                bake_seconds=config.region_bake_seconds,
                failure_threshold=1),
            rollback=RollbackSpec(enable=True))

    # -- one tick of control-plane work ---------------------------------
    def reconcile_regions(self, on_crash: "Optional[object]" = None,
                          monitor: "Optional[FederationMonitor]" = None,
                          ) -> int:
        """Run every live regional controller once (federation pass is
        the caller's job). Returns reconciles performed; a detonating
        crash fuse replaces the affected incarnation in place."""
        reconciles = 0
        for name in sorted(self.regions):
            region = self.regions[name]
            if region.op is None:
                continue
            try:
                policy = self.region_policy(name)
                region.op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                            policy)
                reconciles += 1
            except OperatorCrash:
                self.fuse.reset()
                region.generation += 1
                self.build_region_op(name)
                if on_crash is not None:
                    on_crash(name, "operator crash mid-reconcile")
            except BuildStateError:
                pass
            except (ApiServerError, ConflictError, NotFoundError):
                pass
            if self.fuse.pending:
                self.fuse.reset()
                region.generation += 1
                self.build_region_op(name)
                if on_crash is not None:
                    on_crash(name, "operator crash (surfaced late)")
            if monitor is not None:
                monitor.sample()
        return reconciles

    def step_clusters(self) -> None:
        self.clock.advance(self.config.reconcile_interval)
        for region in self.regions.values():
            region.cluster.step()
        self.sessions.tick()

    # -- convergence checks ---------------------------------------------
    def region_converged(self, name: str, revision: str) -> bool:
        region = self.regions[name]
        try:
            nodes = region.cluster.list_nodes()
            pods = region.cluster.list_pods(namespace=NS)
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != self.config.nodes_per_region:
            return False
        done = str(UpgradeState.DONE)
        for node in nodes:
            if node.metadata.labels.get(self.keys.state_label) != done:
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
        runtime = [p for p in pods if p.controller_owner() is not None]
        if len(runtime) != len(nodes):
            return False
        return all(
            p.metadata.labels.get(POD_CONTROLLER_REVISION_HASH_LABEL)
            == revision and p.is_ready() for p in runtime)

    def shares_all_zero(self) -> bool:
        for region in self.regions.values():
            for ds in region.cluster.list_daemon_sets(NS):
                if ds.metadata.name != "libtpu":
                    continue
                share = self.ledger.share_from(ds.metadata.annotations)
                if share not in (None, 0):
                    return False
        return True


class FederationMonitor:
    """Ground-truth auditor for one federation episode: reads every
    region cluster DIRECTLY (below the gateways, below the ledger) and
    asserts the three federation invariants at every sample."""

    def __init__(self, sim: FederationFleetSim) -> None:
        self.sim = sim
        self.violations: "list[InvariantViolation]" = []
        self.trace: "list[str]" = []
        self.samples = 0
        self.max_joint_unavailable = 0
        #: revision -> bake-stamp epoch observed on the canary DS.
        self._baked: "dict[str, float]" = {}
        #: quarantined revisions observed anywhere.
        self.quarantined: "set[str]" = set()
        #: region -> last observed newest DS revision.
        self._last_revision: "dict[str, str]" = {}
        self._initial_revision: "dict[str, str]" = {}
        #: canary-halt -> fleet-quarantine-complete latency evidence.
        self.halt_seen_at: Optional[float] = None
        self.fleet_quarantined_at: Optional[float] = None
        #: session drops already converted into violations (each new
        #: drop is reported exactly once).
        self._session_drops_seen = 0
        for name, region in sim.regions.items():
            revision = region.cluster.latest_revision_hash(NS, "libtpu")
            self._initial_revision[name] = revision
            self._last_revision[name] = revision

    def _now(self) -> float:
        return self.sim.clock.now()

    def _record(self, line: str) -> None:
        self.trace.append(f"[t={self._now():g}] {line}")

    def _violate(self, invariant: str, subject: str,
                 detail: str) -> None:
        violation = InvariantViolation(invariant, self._now(), subject,
                                       detail)
        self.violations.append(violation)
        self._record(violation.describe())
        logger.error("%s", violation.describe())

    def sample(self) -> None:
        """One ground-truth audit: call after every mutation batch
        (each region reconcile, each federation pass, each clock
        step)."""
        sim = self.sim
        self.samples += 1
        now = self._now()
        budget = sim.config.global_budget
        joint = 0
        per_region: "dict[str, int]" = {}
        for name, region in sorted(sim.regions.items()):
            nodes = consume_transient(region.cluster.list_nodes)
            unavailable = sum(
                1 for node in nodes
                if node.is_unschedulable() or not node.is_ready())
            per_region[name] = unavailable
            joint += unavailable
        self.max_joint_unavailable = max(self.max_joint_unavailable,
                                         joint)
        if joint > budget:
            self._violate(
                "global-budget", "fleet",
                f"joint unavailability {joint} "
                f"({per_region}) exceeds the global budget {budget} — "
                f"the per-region shares jointly overdrew")
        # durable federation facts, observed from the clusters alone
        quarantine_key = sim.keys.quarantined_revision_annotation
        bake_key = sim.fed_keys.bake_passed_annotation
        regions_quarantined = 0
        for name, region in sorted(sim.regions.items()):
            daemon_sets = consume_transient(
                lambda c=region.cluster: c.list_daemon_sets(NS))
            ds = next((d for d in daemon_sets
                       if d.metadata.name == "libtpu"), None)
            if ds is None:
                continue
            quarantined = ds.metadata.annotations.get(quarantine_key)
            if quarantined:
                regions_quarantined += 1
                if quarantined not in self.quarantined:
                    self.quarantined.add(quarantined)
                    self._record(f"revision {quarantined!r} "
                                 f"quarantined (first seen on region "
                                 f"{name})")
                    if self.halt_seen_at is None:
                        self.halt_seen_at = now
            if name == sim.canary:
                stamp = ds.metadata.annotations.get(bake_key, "")
                revision, _, passed_at = stamp.partition(":")
                if revision and passed_at \
                        and revision not in self._baked:
                    try:
                        self._baked[revision] = float(passed_at)
                        self._record(f"bake stamp observed: "
                                     f"{revision!r} at {passed_at}")
                    except ValueError:
                        pass
        # session-zero-drop: a pre-shift-enabled fleet must never
        # have dropped a session (capacity deficits are absorbed by
        # ready reservations; the fleet model records the remainder)
        if sim.config.session_pre_shift \
                and sim.sessions.drops_total > self._session_drops_seen:
            dropped = (sim.sessions.drops_total
                       - self._session_drops_seen)
            self._session_drops_seen = sim.sessions.drops_total
            recent = ", ".join(
                f"t={at:g} {region} -{n}"
                for at, region, n in sim.sessions.drop_events[-3:])
            self._violate(
                "session-zero-drop", "sessions",
                f"{dropped} interactive session(s) dropped — a region "
                f"admission opened a capacity deficit with no ready "
                f"pre-shift reserve ({recent})")
        if self.quarantined and self.fleet_quarantined_at is None \
                and regions_quarantined == len(sim.regions):
            self.fleet_quarantined_at = now
            self._record(
                f"fleet quarantine complete "
                f"({now - (self.halt_seen_at or now):g}s after the "
                f"first verdict)")
        self._check_containment(now)

    def _check_containment(self, now: float) -> None:
        """canary-containment: a non-canary region's DS may only move
        to (a) its own initial revision (a rollback) or (b) a revision
        whose fleet bake stamp exists with the bake elapsed and which
        carries no quarantine verdict; and no pod of a quarantined
        revision may exist outside the canary region."""
        sim = self.sim
        bake_seconds = sim.config.bake_seconds
        for name, region in sorted(sim.regions.items()):
            newest = consume_transient(
                lambda c=region.cluster:
                c.latest_revision_hash(NS, "libtpu"))
            if newest != self._last_revision.get(name):
                self._record(f"region {name} DS revision "
                             f"{self._last_revision.get(name)!r} -> "
                             f"{newest!r}")
                if name != sim.canary \
                        and newest != self._initial_revision[name]:
                    stamped = self._baked.get(newest)
                    if newest in self.quarantined:
                        self._violate(
                            "canary-containment", name,
                            f"non-canary region admitted quarantined "
                            f"revision {newest!r}")
                    elif stamped is None:
                        self._violate(
                            "canary-containment", name,
                            f"non-canary region admitted revision "
                            f"{newest!r} with NO fleet bake-passed "
                            f"stamp")
                    elif now < stamped + bake_seconds:
                        self._violate(
                            "canary-containment", name,
                            f"non-canary region admitted revision "
                            f"{newest!r} only {now - stamped:g}s into "
                            f"the {bake_seconds}s bake")
                self._last_revision[name] = newest
            if name == sim.canary or not self.quarantined:
                continue
            pods = consume_transient(
                lambda c=region.cluster: c.list_pods(namespace=NS))
            for pod in pods:
                pod_hash = pod.metadata.labels.get(
                    POD_CONTROLLER_REVISION_HASH_LABEL)
                if pod_hash in self.quarantined:
                    self._violate(
                        "canary-containment",
                        f"pod {pod.metadata.name}",
                        f"pod of quarantined revision {pod_hash!r} "
                        f"exists in non-canary region {name}")

    def final_check(self, expect_quarantine: Optional[str]) -> None:
        """federation-resume residue audit: every share stamp back to
        0 (or never granted), every pre-shift reservation→ready pair
        released (verified directly AND through the fsck registry's
        torn-pair audit on the region DaemonSets), and — in the
        containment flavor — the quarantine record standing on EVERY
        region, which is what a recovered region re-verifies before
        admitting anything."""
        sim = self.sim
        preshift_keys = (
            sim.fed_keys.preshift_reservation_annotation,
            sim.fed_keys.preshift_ready_annotation)
        auditor = StateAuditor(default_registry(), clock=sim.clock)
        for name, region in sorted(sim.regions.items()):
            for ds in region.cluster.list_daemon_sets(NS):
                if ds.metadata.name != "libtpu":
                    continue
                share = sim.ledger.share_from(ds.metadata.annotations)
                if share not in (None, 0):
                    self._violate(
                        "federation-resume", name,
                        f"budget-share residue survived convergence: "
                        f"stamp still grants {share} node(s)")
                for key in preshift_keys:
                    value = ds.metadata.annotations.get(key)
                    if value is not None:
                        self._violate(
                            "federation-resume", name,
                            f"pre-shift residue survived convergence: "
                            f"{key}={value!r} (the release patch "
                            f"deletes BOTH stamps; a survivor means a "
                            f"torn or skipped release)")
                for finding in auditor.scan([], daemon_sets=[ds]):
                    if finding.key in preshift_keys:
                        self._violate(
                            "federation-resume", name,
                            f"fsck flagged pre-shift stamp "
                            f"{finding.key} as "
                            f"{finding.classification}: "
                            f"{finding.reason}")
                if expect_quarantine is not None:
                    recorded = ds.metadata.annotations.get(
                        sim.keys.quarantined_revision_annotation)
                    if recorded != expect_quarantine:
                        self._violate(
                            "federation-resume", name,
                            f"quarantine record for "
                            f"{expect_quarantine!r} missing after "
                            f"convergence (found {recorded!r}) — a "
                            f"recovered region could re-admit the "
                            f"condemned revision")

    def report(self, seed: int) -> str:
        lines = [f"federation run seed={seed}: "
                 f"{len(self.violations)} violation(s), "
                 f"{self.samples} samples, max joint unavailability "
                 f"{self.max_joint_unavailable}/"
                 f"{self.sim.config.global_budget}"]
        lines += [v.describe() for v in self.violations]
        if self.violations:
            lines.append("--- trace (replay with "
                         f"run_federation_soak(seed={seed})) ---")
            lines += self.trace[-120:]
        return "\n".join(lines)


def _install_region_api_bursts(sim: FederationFleetSim,
                               schedule: FaultSchedule) -> None:
    for event in schedule.by_kind(FAULT_API_BURST):
        region_name, _, operation = event.target.partition(":")
        region = sim.regions.get(region_name)
        if region is None:
            continue
        region.cluster.schedule_at(
            event.at,
            lambda c=region.cluster, op=operation, n=event.param:
            c.inject_api_errors(op, n))


def _run_federation_episode(seed: int, config: FederationChaosConfig,
                            schedule: FaultSchedule,
                            target_of: "object",
                            converged: "object",
                            expect_quarantine: "Optional[str]",
                            ) -> "tuple[FederationFleetSim, FederationMonitor, ChaosReport]":
    """Shared episode loop of both federation gates. ``target_of(now)``
    yields the federation's target revision; ``converged(sim)`` the
    episode's convergence predicate."""
    sim = FederationFleetSim(config)
    clock = sim.clock
    monitor = FederationMonitor(sim)
    _install_region_api_bursts(sim, schedule)

    crash_events = sorted(schedule.by_kind(FAULT_OPERATOR_CRASH),
                          key=lambda e: e.at)
    crash_index = 0
    region_kills = sorted(schedule.by_kind(FAULT_REGION_KILL),
                          key=lambda e: e.at)
    region_kill_index = 0
    fed_kills = sorted(schedule.by_kind(FAULT_FED_KILL),
                       key=lambda e: e.at)
    fed_kill_index = 0
    for event in schedule.by_kind(FAULT_FED_PARTITION):
        gateway = sim.regions[event.target].gateway
        gateway.add_window(event.at, event.until)
    # watch-path faults: a delay window buffers the region's event
    # delivery (every subscriber's cache silently freezes — the
    # federation's staleness bound must notice); a break stops the
    # federation's streams for one region (param parity: silent drop
    # vs in-band 410 expiry — both repair via a region-local relist)
    for event in schedule.by_kind(FAULT_WATCH_DELAY):
        region = sim.regions.get(event.target)
        if region is not None:
            region.cluster.delay_watch_events(
                event.at, event.until, seed=event.param)
    for event in schedule.by_kind(FAULT_WATCH_BREAK):
        region = sim.regions.get(event.target)
        if region is not None:
            breaker = (region.gateway.drop_streams
                       if event.param % 2 == 0
                       else region.gateway.expire_streams)
            region.cluster.schedule_at(
                event.at, lambda b=breaker: b() and None)
    region_kills_fired = 0
    fed_kills_fired = 0
    fed_saw_partition = False
    fed_restart_at: Optional[float] = None
    pending_region_restarts: "list[tuple[float, str]]" = []
    fed_reconciles = 0
    region_reconciles = 0

    def on_crash(region: str, reason: str) -> None:
        monitor.trace.append(
            f"[t={clock.now():g}] region {region} controller restart "
            f"({reason}) — rebuilt from the region's state alone")

    steps = 0
    quiesce_ticks = 0
    is_converged = False
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        # regional-controller kills / replacements
        while region_kill_index < len(region_kills) \
                and region_kills[region_kill_index].at <= now:
            event = region_kills[region_kill_index]
            region_kill_index += 1
            region = sim.regions[event.target]
            if region.op is not None:
                region.op = None
                region_kills_fired += 1
                monitor.trace.append(
                    f"[t={now:g}] region {event.target} controller "
                    f"KILLED (replacement at t={event.until:g})")
            pending_region_restarts.append((event.until, event.target))
        due = [p for p in pending_region_restarts if p[0] <= now]
        pending_region_restarts = [p for p in pending_region_restarts
                                   if p[0] > now]
        for _, name in due:
            sim.regions[name].generation += 1
            sim.build_region_op(name)
            monitor.trace.append(
                f"[t={now:g}] region {name} replacement controller "
                f"started — re-verifies quarantine/share stamps from "
                f"its own cluster before admitting anything")
        # federation-controller kill / replacement
        while fed_kill_index < len(fed_kills) \
                and fed_kills[fed_kill_index].at <= now:
            event = fed_kills[fed_kill_index]
            fed_kill_index += 1
            if sim.fed is not None:
                sim.fed = None
                fed_kills_fired += 1
                fed_restart_at = event.until
                monitor.trace.append(
                    f"[t={now:g}] federation controller KILLED "
                    f"(replacement at t={event.until:g})")
        if sim.fed is None and fed_restart_at is not None \
                and fed_restart_at <= now:
            sim.build_fed()
            fed_restart_at = None
            monitor.trace.append(
                f"[t={now:g}] federation controller replacement "
                f"#{sim.fed_generation} started — zero in-memory "
                f"state, resumes from the regions' durable stamps")
        # arm operator crashes (the fuse is shared by every region's
        # provider: the schedule says a controller dies around now,
        # and whichever regional controller writes next dies)
        while crash_index < len(crash_events) \
                and crash_events[crash_index].at <= now:
            event = crash_events[crash_index]
            crash_index += 1
            sim.fuse.arm(event.param, after=event.param % 2 == 1)
        target = target_of(now)
        if sim.fed is not None and target:
            if any(r.gateway.partitioned()
                   for r in sim.regions.values()):
                fed_saw_partition = True
            sim.fed.reconcile(target)
            fed_reconciles += 1
        monitor.sample()
        region_reconciles += sim.reconcile_regions(on_crash=on_crash,
                                                   monitor=monitor)
        if (now > schedule.last_fault_time
                and not sim.fuse.armed and not sim.fuse.pending
                and sim.fed is not None
                and not pending_region_restarts
                and converged(sim)):
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        sim.step_clusters()
        monitor.sample()

    if is_converged:
        monitor.final_check(expect_quarantine)
    else:
        status = sim.fed.last_status if sim.fed is not None else None
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"federated fleet did not converge within "
                   f"{config.max_steps} steps ({clock.now():g}s "
                   f"virtual); last status: {status}"))

    # harness sanity: the episode must have exercised what it gates
    if region_kills_fired < 1:
        monitor._violate("harness", "runner",
                         "no regional-controller kill fired")
    if fed_kills_fired < 1:
        monitor._violate("harness", "runner",
                         "no federation-controller kill fired")
    if sim.fuse.fired_total < 1:
        monitor._violate("harness", "runner",
                         "no operator crash fired — the schedule's "
                         "crash events never detonated")
    partitioned_calls = sum(r.gateway.partitioned_calls
                            for r in sim.regions.values())
    if fed_saw_partition and partitioned_calls == 0:
        # the federation ran passes WHILE a partition window was
        # active, yet never touched a cut gateway — the fault model
        # is broken (windows the fed-kill fully covered are exempt:
        # a dead controller cannot probe anything)
        monitor._violate("harness", "runner",
                         "a federation pass ran during a partition "
                         "window but no call ever hit the cut — the "
                         "windows proved nothing")

    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=sim.fuse.fired_total,
        leader_handovers=region_kills_fired + fed_kills_fired,
        operator_incarnations=sim.region_incarnations
        + sim.fed_generation,
        watch_gaps=0,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=region_reconciles + fed_reconciles,
        trace=list(monitor.trace))
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return sim, monitor, report


def run_federation_soak(seed: int,
                        config: Optional[FederationChaosConfig] = None,
                        ) -> ChaosReport:
    """The federation robustness gate: a full region-as-canary global
    rollout to :data:`FED_TARGET_REVISION` under regional-controller
    kills, federation↔region partitions, a federation-controller kill
    and regional operator crashes — deterministic in ``seed``. Green
    means zero ``global-budget`` / ``canary-containment`` /
    ``federation-resume`` violations AND full convergence: every
    region done on the target, the bake stamp durable on the canary
    region, every share stamp back to zero."""
    config = config or FederationChaosConfig()
    schedule = FaultSchedule.generate_federation(
        seed, list(config.regions), horizon=config.horizon)
    promote_at = config.horizon / 2.0

    def target_of(now: float) -> str:
        return (FED_FINAL_REVISION if now >= promote_at
                else FED_TARGET_REVISION)

    def converged(sim: FederationFleetSim) -> bool:
        if not all(sim.region_converged(name, FED_FINAL_REVISION)
                   for name in sim.regions):
            return False
        canary = sim.regions[sim.canary]
        stamped = ""
        for ds in canary.cluster.list_daemon_sets(NS):
            if ds.metadata.name == "libtpu":
                stamped = ds.metadata.annotations.get(
                    sim.fed_keys.bake_passed_annotation, "")
        if not stamped.startswith(f"{FED_FINAL_REVISION}:"):
            return False
        return sim.shares_all_zero()

    sim, monitor, report = _run_federation_episode(
        seed, config, schedule, target_of=target_of,
        converged=converged, expect_quarantine=None)
    if monitor.max_joint_unavailable == 0:
        # harness sanity: a rollout that never made anything
        # unavailable exercised no budget at all
        report.violations.append(InvariantViolation(
            invariant="harness", at=report.total_seconds,
            subject="monitor",
            detail="joint unavailability never rose above zero — the "
                   "episode upgraded nothing, so the global-budget "
                   "audit proved nothing"))
    if config.session_pre_shift and sim.sessions.shift_ticks == 0:
        # harness sanity: the zero-drop audit only proves something
        # if sessions actually rode a pre-shift reserve at least once
        report.violations.append(InvariantViolation(
            invariant="harness", at=report.total_seconds,
            subject="sessions",
            detail="no session was ever pre-shifted — every capacity "
                   "deficit missed the reserves, so the "
                   "session-zero-drop audit proved nothing"))
    return report


def run_federation_bad_revision_soak(
        seed: int,
        config: Optional[FederationChaosConfig] = None) -> ChaosReport:
    """The containment gate: the federation's target becomes a
    revision whose pods can never become Ready. The canary REGION's
    own RolloutGuard must halt and roll the region back; the
    federation must lift the quarantine to every region in the same
    pass(es) — through a canary-region controller kill, a
    federation↔region partition and a federation-controller kill —
    and no non-canary region may ever carry the condemned revision
    (DS or pod). Convergence: every region back on its initial
    revision, the quarantine record standing on EVERY region's
    DaemonSet, shares back to zero."""
    config = config or FederationChaosConfig()
    if not config.bad_revision:
        config = copy.deepcopy(config)
        config.bad_revision = BAD_REVISION_HASH
    # the canary choice is config-deterministic (FederationFleetSim
    # picks the lowest-utilization region at t=0): recompute it here so
    # the schedule can target it before the sim exists
    names = list(config.regions)
    canary_name = min(
        names,
        key=lambda name: (config.region_utilization(
            names.index(name), 0.0), name))
    schedule = FaultSchedule.generate_federation_bad_revision(
        seed, names, canary_name, horizon=config.horizon)
    bad_events = schedule.by_kind(FAULT_BAD_REVISION)
    bad_at = bad_events[0].at if bad_events else 0.0

    def target_of(now: float) -> str:
        return config.bad_revision if now >= bad_at else ""

    def converged(sim: FederationFleetSim) -> bool:
        for name, region in sim.regions.items():
            # recovery target: the fleet's initial revision (the
            # canary region rolled back; nobody else ever moved)
            if not sim.region_converged(name, "old"):
                return False
            ds = next((d for d in region.cluster.list_daemon_sets(NS)
                       if d.metadata.name == "libtpu"), None)
            if ds is None or ds.metadata.annotations.get(
                    sim.keys.quarantined_revision_annotation) \
                    != config.bad_revision:
                return False
        return sim.shares_all_zero()

    _, monitor, report = _run_federation_episode(
        seed, config, schedule, target_of=target_of,
        converged=converged, expect_quarantine=config.bad_revision)
    if monitor.halt_seen_at is None:
        monitor._violate(
            "harness", "monitor",
            "no quarantine verdict observed — the bad revision never "
            "tripped the canary region's guard, so the containment "
            "gate proved nothing")
        report.violations = list(monitor.violations)
    if monitor.halt_seen_at is not None \
            and monitor.fleet_quarantined_at is not None:
        monitor.trace.append(
            f"[t={report.total_seconds:g}] canary-halt -> "
            f"fleet-quarantine latency: "
            f"{monitor.fleet_quarantined_at - monitor.halt_seen_at:g}s")
        report.trace = list(monitor.trace)
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    return report
