"""The chaos soak runner: real state machines vs a seeded fault storm.

One :func:`run_chaos_soak` call is a full deterministic episode:

1. Build a virtual GKE TPU fleet (simulate.build_fleet) with a
   multislice workload, roll the runtime DaemonSet (rollout #1), and
   schedule a SECOND revision bump mid-horizon — write traffic is
   guaranteed deep into the fault window, so every armed operator crash
   detonates.
2. Install the seed's :class:`~tpu_operator_libs.chaos.schedule.
   FaultSchedule` via :class:`~tpu_operator_libs.chaos.injector.
   ChaosInjector`.
3. Tick virtual time. Each tick, the current operator *incarnation*
   (leader-elected ClusterUpgradeStateManager + NodeRemediationManager
   sharing a crash fuse) reconciles; faults fire between ticks; the
   :class:`~tpu_operator_libs.chaos.invariants.InvariantMonitor`
   drains the watch stream and asserts safety after every mutation.
4. Operator crash–restart: when the fuse detonates mid-pass, the
   incarnation is discarded and a brand-new one — fresh managers, fresh
   provider, fresh elector identity, zero in-memory state — takes over
   from node labels/annotations alone. Leader loss works the same way:
   a stolen Lease demotes the incumbent and a fresh instance wins the
   lock after expiry.
5. After the last scheduled fault heals, the run must converge: every
   node upgrade-done on the final revision, remediation-clean,
   schedulable, Ready; every cordon paired with an uncordon.

The report carries the seed, fault kinds, crash/handover counts, the
violation list and the replay trace — rerunning the seed reproduces the
episode exactly (the only entropy is the seed).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from tpu_operator_libs.api.remediation_policy import (
    PrecursorPolicySpec,
    ReconfigurationPolicySpec,
    RemediationPolicySpec,
)
from tpu_operator_libs.api.upgrade_policy import (
    CapacityBudgetSpec,
    DrainSpec,
    IntOrString,
    MaintenanceWindowSpec,
    PredictorSpec,
    PreflightSpec,
    TrafficClassSpec,
    UpgradePolicySpec,
)
from tpu_operator_libs.chaos.injector import (
    BAD_REVISION_HASH,
    ChaosInjector,
    CrashingStateProvider,
    OperatorCrash,
    consume_transient,
)
from tpu_operator_libs.chaos.invariants import (
    CapacityExpectation,
    DagExpectation,
    InvariantMonitor,
    InvariantViolation,
    ReconfigExpectation,
    RolloutExpectation,
    ShardExpectation,
    WindowExpectation,
)
from tpu_operator_libs.chaos.schedule import (
    FAULT_NODE_KILL,
    FAULT_STATE_CORRUPTION,
    FAULT_TRAFFIC_SPIKE,
    FaultSchedule,
)
from tpu_operator_libs.fsck import StateAuditor, default_registry
from tpu_operator_libs.chaos.serving import (
    CapacityLog,
    DiurnalTrace,
    ServingFleetSim,
    SpikeWindow,
    assign_traffic,
)
from tpu_operator_libs.consts import (
    GKE_NODEPOOL_LABEL,
    IN_PROGRESS_STATES,
    POD_CONTROLLER_REVISION_HASH_LABEL,
    RemediationKeys,
    RemediationState,
    TopologyKeys,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    NotFoundError,
)
from tpu_operator_libs.k8s.fake import FakeCluster
from tpu_operator_libs.k8s.leaderelection import (
    LeaderElectionConfig,
    LeaderElector,
)
from tpu_operator_libs.remediation.state_machine import (
    NodeRemediationManager,
)
from tpu_operator_libs.simulate import (
    NS,
    RUNTIME_LABELS,
    WORKLOAD_NS,
    FleetSpec,
    build_fleet,
    restore_workload_pods,
    seed_spare_pool,
)
from tpu_operator_libs.upgrade.state_manager import (
    BuildStateError,
    ClusterUpgradeStateManager,
)
from tpu_operator_libs.util import FakeClock

logger = logging.getLogger(__name__)

#: Revision hashes of the two rollouts every soak performs. build_fleet
#: already rolls "old" -> "new"; the runner bumps to FINAL_REVISION at
#: horizon/2 so the fleet is mid-rollout when late faults land.
FINAL_REVISION = "new2"


@dataclass
class ChaosConfig:
    """Knobs of one soak episode (defaults are the tier-1 shape)."""

    n_slices: int = 3
    hosts_per_slice: int = 2
    pod_recreate_delay: float = 5.0
    pod_ready_delay: float = 15.0
    reconcile_interval: float = 10.0
    #: Fault windows live inside [0, horizon); convergence is only
    #: checked after the horizon.
    horizon: float = 600.0
    #: Hard step cap (steps * reconcile_interval bounds virtual time).
    max_steps: int = 1200
    #: How many fault kinds ride along besides operator-crash.
    extra_fault_kinds: int = 4
    #: Flat-planner budgets — strict, so the monitor's max-unavailable
    #: invariant is exact (the slice planner may legally overdraw).
    max_unavailable: IntOrString = "50%"
    max_parallel_upgrades: int = 0
    #: Bucket worker pool size for the upgrade machine (state_manager
    #: parallel_workers). ON by default: the chaos gate is exactly
    #: where concurrency bugs in the fan-out must surface — budget
    #: admission stays serialized, so the invariants must hold under
    #: any thread interleaving. 0 restores the serial reference walk.
    parallel_workers: int = 4
    lease_namespace: str = "kube-system"
    lease_name: str = "chaos-operator-leader"

    def upgrade_policy(self) -> UpgradePolicySpec:
        return UpgradePolicySpec(
            auto_upgrade=True,
            max_parallel_upgrades=self.max_parallel_upgrades,
            max_unavailable=self.max_unavailable,
            topology_mode="flat",
            drain=DrainSpec(enable=True, force=True,
                            timeout_seconds=300),
            # The cost-aware predictive planner runs LIVE under the
            # standing gate: LPT reordering + the phase-stamp learning
            # seam must hold every invariant under compound faults and
            # crash-restarts (each incarnation relearns from the
            # durable stamps alone).
            predictor=PredictorSpec(enable=True),
            # The capacity budget controller runs LIVE too: with no
            # serving signal wired it must fail open to the static
            # budget EXACTLY — the standing gates pin that under
            # compound faults (the budget soak is where it modulates).
            capacity=CapacityBudgetSpec(enable=True))

    def remediation_policy(self) -> RemediationPolicySpec:
        policy = RemediationPolicySpec(
            enable=True,
            max_concurrent=1,
            max_unavailable="50%",
            restart_attempts=1,
            max_attempts=4,
            action_timeout_seconds=300,
            settle_seconds=60,
            revalidate_timeout_seconds=600,
            drain=DrainSpec(enable=True, force=True,
                            timeout_seconds=240))
        policy.detection.not_ready_grace_seconds = 120
        return policy


@dataclass
class ChaosReport:
    """Outcome of one seeded soak episode."""

    seed: int
    converged: bool
    violations: list[InvariantViolation]
    fault_kinds: tuple[str, ...]
    crashes_fired: int
    leader_handovers: int
    operator_incarnations: int
    watch_gaps: int
    total_seconds: float
    steps: int
    reconciles: int
    report_text: str = ""
    trace: list[str] = field(default_factory=list)
    #: decision-audit records mirrored into the monitor (obs/ teeth
    #: evidence: 0 with a wired feed means the audit recorded nothing).
    decisions_recorded: int = 0
    #: explain() probes run against parked nodes (each must have
    #: produced a non-empty blocking chain or a violation exists).
    explains_probed: int = 0
    #: gate-specific outcome samples (the bench readers' feed): e.g.
    #: the precursor gate's per-victim slice downtime and the serving
    #: sim's drop attribution. Purely informational — never consulted
    #: by ``ok``.
    stats: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.converged and not self.violations

    def summary(self) -> str:
        verdict = "OK" if self.ok else "FAILED"
        return (f"chaos seed={self.seed}: {verdict} — "
                f"{len(self.fault_kinds)} fault kinds "
                f"{sorted(self.fault_kinds)}, "
                f"{self.crashes_fired} operator crash(es), "
                f"{self.leader_handovers} leader handover(s), "
                f"{self.watch_gaps} watch gap(s), "
                f"{len(self.violations)} violation(s), "
                f"converged={self.converged} in {self.total_seconds:g}s "
                f"virtual / {self.steps} steps / "
                f"{self.reconciles} reconciles")


class _OperatorIncarnation:
    """One operator process-lifetime: fresh managers, fresh elector.

    Everything here is rebuilt from scratch on crash/demotion — the ONLY
    state that survives an incarnation is what lives on the cluster
    (node labels, annotations, the Lease), which is precisely the
    durability claim the harness proves.
    """

    def __init__(self, cluster: FakeCluster, clock: FakeClock,
                 keys: UpgradeKeys, rem_keys: RemediationKeys,
                 config: ChaosConfig, injector: ChaosInjector,
                 identity: str, with_reconfigurer: bool = False,
                 serving: "Optional[ServingFleetSim]" = None,
                 monitor: "Optional[InvariantMonitor]" = None,
                 precursor_source: "object" = None,
                 fsck_registry: "object" = None,
                 fsck_repair_log: "Optional[list]" = None) -> None:
        # The event-driven scheduling layer runs INSIDE the gate: both
        # machines carry a live ReconcileNudger (completion nudges +
        # deadline timer wheel + eager slot refill all active), exactly
        # like the packaged operator. The tick-driven soak loop owns
        # the clock, so it consumes the nudger's due slots each tick —
        # the wakeups add no new reconcile instants to the seeded
        # replay, but every seam executes under chaos. Like the rest of
        # an incarnation, the nudger dies with the process: deadlines
        # must be re-derivable from durable stamps alone.
        from tpu_operator_libs.upgrade.nudger import ReconcileNudger

        self.nudger = ReconcileNudger(clock=clock)
        provider = CrashingStateProvider(
            cluster, keys, None, clock, sync_timeout=5.0,
            poll_interval=1.0, fuse=injector.fuse)
        self.upgrade = ClusterUpgradeStateManager(
            cluster, keys, clock=clock, async_workers=False,
            provider=provider, poll_interval=1.0, sync_timeout=5.0,
            parallel_workers=config.parallel_workers,
            nudger=self.nudger)
        if serving is not None:
            # the budget gate's serving fleet: the drain gate guards
            # every eviction against in-flight generations, and the
            # capacity controller reads the same endpoints as its
            # budget signal. Both die with the incarnation — the
            # controller re-derives its picture from the live
            # endpoints on its first pass (the crash-resume claim).
            from tpu_operator_libs.health.serving_gate import (
                ServingDrainGate,
            )

            self.upgrade.with_eviction_gate(
                ServingDrainGate(serving.resolver))
            self.upgrade.with_serving_signal(serving.source)
            # the prewarm seams (no-ops unless the policy declares
            # traffic classes + prewarm): the sim is the serving side
            # that brings replacement replicas up and retires them
            self.upgrade.with_prewarm_hooks(
                serving.prewarm_readiness, serving.prewarm_release)
        rem_provider = CrashingStateProvider(
            cluster, rem_keys, None, clock,  # type: ignore[arg-type]
            sync_timeout=5.0, poll_interval=1.0, fuse=injector.fuse)
        reconfigurer = None
        if with_reconfigurer:
            # the remap's durable writes run through the same crash
            # fuse as the state machines' label commits, so operator
            # crashes land INSIDE the reserve→join→release sequence
            from tpu_operator_libs.topology.reconfigurer import (
                SliceReconfigurer,
            )

            reconfigurer = SliceReconfigurer(
                cluster,
                TopologyKeys(driver=keys.driver, domain=keys.domain),
                remediation_keys=rem_keys, upgrade_keys=keys,
                clock=clock, nudger=self.nudger,
                guard=injector.fuse.guard)
        precursor = None
        rem_gate = None
        if precursor_source is not None:
            # condemn-before-fail: a FRESH FailurePrecursorModel per
            # incarnation — its memory dies with the process and must
            # resume from the durable per-node seed annotations alone
            # (the crash-resume claim of the predictive arc). The
            # at-risk planned drain runs through the serving gate, so
            # a still-serving node quiesces before its pods go.
            spec = config.remediation_policy().precursor
            if spec is not None and spec.enable:
                from tpu_operator_libs.health.precursor import (
                    FailurePrecursorModel,
                )

                precursor = FailurePrecursorModel(
                    keys=rem_keys, clock=clock,
                    smoothing=spec.smoothing,
                    rate_threshold_per_hour=spec.rate_threshold_per_hour,
                    min_observations=spec.min_observations)
                if serving is not None:
                    from tpu_operator_libs.health.serving_gate import (
                        ServingDrainGate,
                    )

                    rem_gate = ServingDrainGate(serving.resolver)
        self.remediation = NodeRemediationManager(
            cluster, rem_keys, upgrade_keys=keys, clock=clock,
            provider=rem_provider, poll_interval=1.0, sync_timeout=5.0,
            nudger=self.nudger, reconfigurer=reconfigurer,
            precursor=precursor,
            precursor_source=(precursor_source
                              if precursor is not None else None),
            eviction_gate=rem_gate)
        self.elector = LeaderElector(
            cluster,
            LeaderElectionConfig(
                namespace=config.lease_namespace, name=config.lease_name,
                identity=identity, lease_duration=30.0,
                renew_deadline=20.0, retry_period=2.0),
            clock=clock)
        self.identity = identity
        # Journey tracing + decision audit run INSIDE every standing
        # gate: the tracer's trace-id annotations ride the crash-fused
        # durable writes, the audit records every admission/hold/abort,
        # and — like everything else here — both die with the
        # incarnation (journeys resume from the durable stamps alone,
        # which is the crash-survival claim the gates now pin). The
        # monitor keeps the cross-incarnation decision log (its
        # ``note_decision`` mirror) and dumps the audit/trace context
        # on any violation.
        from tpu_operator_libs.obs import OperatorObservability

        self.obs = OperatorObservability(keys, clock=clock)
        self.upgrade.with_observability(self.obs)
        if monitor is not None:
            self.obs.audit.mirror = monitor.note_decision
            monitor.obs_source = lambda: self.obs
        # The durable-state fsck pair: a fresh auditor per incarnation
        # (its clean-digest cache is an optimization, never state — it
        # dies with the process and the next incarnation rescans), and
        # a janitor whose repairs run through the SAME crash fuse as
        # the machines' durable writes. Only the repair log survives
        # the incarnation (injected by the harness): audited explain()
        # chains must outlive the process that wrote them.
        self.auditor = None
        self.janitor = None
        if fsck_registry is not None:
            from tpu_operator_libs.fsck import Janitor, StateAuditor

            self.auditor = StateAuditor(fsck_registry, clock=clock,
                                        audit=self.obs.audit)
            self.janitor = Janitor(
                cluster, fsck_registry, keys, remediation_keys=rem_keys,
                guard=injector.fuse.guard, audit=self.obs.audit,
                clock=clock, repair_log=fsck_repair_log)


def run_chaos_soak(seed: int,
                   config: Optional[ChaosConfig] = None) -> ChaosReport:
    """Run one seeded chaos episode; deterministic in ``seed``."""
    config = config or ChaosConfig()
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay,
        multislice_jobs=(
            ("chaos-job", tuple(range(config.n_slices))),))
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    node_names = [n.metadata.name for n in cluster.list_nodes()]

    schedule = FaultSchedule.generate(
        seed, node_names, horizon=config.horizon,
        extra_kinds=config.extra_fault_kinds)
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name)
    injector.install()
    # rollout #2 mid-horizon: guarantees write traffic after every
    # armed crash, and lands late faults on a mid-rollout fleet
    cluster.schedule_at(
        config.horizon / 2.0,
        lambda: cluster.bump_daemon_set_revision(NS, "libtpu",
                                                 FINAL_REVISION))

    upgrade_policy = config.upgrade_policy()
    remediation_policy = config.remediation_policy()
    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        max_unavailable=upgrade_policy.max_unavailable,
        remediation_max_unavailable=remediation_policy.max_unavailable,
        max_parallel_upgrades=config.max_parallel_upgrades)

    incarnations = 1
    handovers = 0
    reconciles = 0
    op = _OperatorIncarnation(cluster, clock, keys, rem_keys, config,
                              injector, identity="operator-1",
                              monitor=monitor)

    def next_incarnation(reason: str) -> _OperatorIncarnation:
        nonlocal incarnations
        incarnations += 1
        injector.fuse.reset()
        monitor.trace.append(
            f"[t={clock.now():g}] operator restart #{incarnations} "
            f"({reason}) — rebuilding managers from cluster state alone")
        return _OperatorIncarnation(
            cluster, clock, keys, rem_keys, config, injector,
            identity=f"operator-{incarnations}", monitor=monitor)

    def converged() -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = cluster.list_pods(namespace=NS)
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != len(node_names):
            return False
        for node in nodes:
            labels = node.metadata.labels
            if labels.get(keys.state_label) != str(UpgradeState.DONE):
                return False
            if labels.get(rem_keys.state_label, ""):
                return False
            if keys.skip_label in labels:
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
        runtime = [p for p in pods
                   if p.controller_owner() is not None]
        if len(runtime) != len(node_names):
            return False
        return all(
            p.metadata.labels.get(POD_CONTROLLER_REVISION_HASH_LABEL)
            == FINAL_REVISION and p.is_ready() for p in runtime)

    steps = 0
    is_converged = False
    quiesce_ticks = 0
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        was_leading = op.elector.is_leader
        op.elector.try_acquire_or_renew()
        if was_leading and not op.elector.is_leader:
            # demoted: a live intruder holds the Lease. The incumbent
            # stops reconciling ON THIS TICK (split-brain safety); a
            # fresh instance contends and resumes from labels once the
            # intruder's lease expires.
            handovers += 1
            op = next_incarnation("leader election lost")
            op.elector.try_acquire_or_renew()
        if op.elector.is_leader:
            injector.arm_due_crashes(now)
            # tick-driven loop owns the clock: drain the nudger's due
            # deadline slots and pending completion flag so the wheel
            # stays bounded (the tick itself is the wakeup here)
            op.nudger.pop_due(now)
            op.nudger.consume_pending()
            try:
                op.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                         remediation_policy)
                op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                     upgrade_policy)
                reconciles += 1
            except OperatorCrash:
                op = next_incarnation("operator crash mid-reconcile")
            except BuildStateError:
                pass  # incomplete snapshot; next tick retries
            except (ApiServerError, ConflictError, NotFoundError):
                pass  # pass aborted on a transient; next tick retries
            if injector.fuse.pending:
                # the crash was swallowed by a broad handler somewhere
                # down the stack — the process is still "dead"
                op = next_incarnation("operator crash (surfaced late)")
        monitor.drain()
        if steps % 5 == 0 and op.upgrade.last_state is not None:
            # the explain probe: every parked node must produce a
            # non-empty blocking-reason chain, answered from in-memory
            # state (no cluster read — injected API faults can't trip
            # it). Subjects come from the monitor's mirror for the
            # same reason.
            for parked in monitor.parked_nodes():
                monitor.audit_explain(parked,
                                      op.upgrade.explain(parked))
        try:
            restore_workload_pods(cluster, fleet)
        except (ApiServerError, TimeoutError):
            pass  # injected fault; the JobSet controller retries too
        monitor.drain()
        if (now > schedule.last_fault_time
                and not injector.fuse.armed
                and not injector.fuse.pending
                and converged()):
            # Converged — but a real operator keeps reconciling in
            # steady state, and the machines clear residual bookkeeping
            # (e.g. a wedge debounce stamp frozen while the node was
            # mid-upgrade) on exactly those quiet passes. Run two of
            # them before the final annotation/pairing audit so the
            # audit measures the system, not the harness's stop timing.
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    if is_converged:
        monitor.final_check()
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"fleet did not converge within {config.max_steps} "
                   f"steps ({clock.now():g}s virtual) after the last "
                   f"fault healed at {schedule.last_fault_time:g}s"))

    # sanity: the harness itself must have exercised what it claims
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))

    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=handovers,
        operator_incarnations=incarnations,
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed)
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


def run_bad_revision_soak(seed: int,
                          config: Optional[ChaosConfig] = None,
                          ) -> ChaosReport:
    """The canary-halt-rollback gate: one seeded episode where the
    runtime DaemonSet is rolled to a revision whose pods can never
    become Ready.

    The operator runs with a canary policy (cohort of 1, failure
    threshold 1, automatic rollback); the monitor's rollout invariants
    prove the fleet halts within one reconcile pass of the threshold
    tripping, that no node newly enters the upgrade flow after the halt
    until the rollback signal, and that no pod of the condemned
    revision is ever minted again; convergence means every node is
    upgrade-done back on the PREVIOUS revision with the quarantine
    annotation still on the DaemonSet. Remediation is disabled for the
    episode: a crash-looping canary pod is also a wedge signal, and the
    gate must attribute the recovery to the upgrade machine's rollback,
    not to the node-remediation ladder (their interplay is covered by
    the main soak gate).
    """
    config = config or ChaosConfig()
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay,
        multislice_jobs=(
            ("chaos-job", tuple(range(config.n_slices))),))
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    node_names = [n.metadata.name for n in cluster.list_nodes()]

    schedule = FaultSchedule.generate_bad_revision(
        seed, node_names, ds_target=f"{NS}/libtpu",
        horizon=config.horizon)
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name)
    injector.install()

    from tpu_operator_libs.api.upgrade_policy import (
        CanaryRolloutSpec,
        RollbackSpec,
    )

    upgrade_policy = config.upgrade_policy()
    upgrade_policy.canary = CanaryRolloutSpec(
        enable=True, canary_count=1, bake_seconds=30,
        failure_threshold=1)
    upgrade_policy.rollback = RollbackSpec(enable=True)
    remediation_policy = config.remediation_policy()
    remediation_policy.enable = False

    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        max_unavailable=upgrade_policy.max_unavailable,
        remediation_max_unavailable=None,
        max_parallel_upgrades=config.max_parallel_upgrades,
        rollout=RolloutExpectation(
            bad_revision=BAD_REVISION_HASH,
            failure_threshold=upgrade_policy.canary.failure_threshold,
            runtime_namespace=NS,
            bad_pod_grace_seconds=(config.pod_recreate_delay
                                   + 3 * config.reconcile_interval)))

    incarnations = 1
    handovers = 0
    reconciles = 0
    op = _OperatorIncarnation(cluster, clock, keys, rem_keys, config,
                              injector, identity="operator-1",
                              monitor=monitor)

    def next_incarnation(reason: str) -> _OperatorIncarnation:
        nonlocal incarnations
        incarnations += 1
        injector.fuse.reset()
        monitor.trace.append(
            f"[t={clock.now():g}] operator restart #{incarnations} "
            f"({reason}) — rebuilding managers from cluster state alone")
        return _OperatorIncarnation(
            cluster, clock, keys, rem_keys, config, injector,
            identity=f"operator-{incarnations}", monitor=monitor)

    #: what the fleet must converge BACK to: the newest revision before
    #: the bad roll (build_fleet's rollout target)
    good_revision = cluster.latest_revision_hash(NS, "libtpu")

    def converged() -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = cluster.list_pods(namespace=NS)
            daemon_sets = cluster.list_daemon_sets(NS)
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != len(node_names):
            return False
        for node in nodes:
            labels = node.metadata.labels
            if labels.get(keys.state_label) != str(UpgradeState.DONE):
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
        runtime = [p for p in pods if p.controller_owner() is not None]
        if len(runtime) != len(node_names):
            return False
        if not all(
                p.metadata.labels.get(POD_CONTROLLER_REVISION_HASH_LABEL)
                == good_revision and p.is_ready() for p in runtime):
            return False
        # the quarantine record must survive convergence: it is what
        # keeps reconcile from ever re-attempting the bad hash
        return any(
            ds.metadata.annotations.get(
                keys.quarantined_revision_annotation)
            == BAD_REVISION_HASH for ds in daemon_sets)

    steps = 0
    is_converged = False
    quiesce_ticks = 0
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        was_leading = op.elector.is_leader
        op.elector.try_acquire_or_renew()
        if was_leading and not op.elector.is_leader:
            handovers += 1
            op = next_incarnation("leader election lost")
            op.elector.try_acquire_or_renew()
        if op.elector.is_leader:
            injector.arm_due_crashes(now)
            # tick-driven loop owns the clock: drain the nudger's due
            # deadline slots and pending completion flag so the wheel
            # stays bounded (the tick itself is the wakeup here)
            op.nudger.pop_due(now)
            op.nudger.consume_pending()
            try:
                op.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                         remediation_policy)
                op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                     upgrade_policy)
                reconciles += 1
            except OperatorCrash:
                op = next_incarnation("operator crash mid-reconcile")
            except BuildStateError:
                pass
            except (ApiServerError, ConflictError, NotFoundError):
                pass
            if injector.fuse.pending:
                op = next_incarnation("operator crash (surfaced late)")
        monitor.drain()
        try:
            restore_workload_pods(cluster, fleet)
        except (ApiServerError, TimeoutError):
            pass
        monitor.drain()
        if (now > schedule.last_fault_time
                and not injector.fuse.armed
                and not injector.fuse.pending
                and converged()):
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    if is_converged:
        monitor.final_check()
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"fleet did not converge back to revision "
                   f"{good_revision!r} within {config.max_steps} steps "
                   f"({clock.now():g}s virtual)"))

    # harness sanity: the episode must have exercised what it gates
    if injector.bad_revisions_rolled == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="bad-revision fault never fired"))
    if monitor.halt_evidence_at is None:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="monitor",
            detail="no halt evidence observed — the bad revision never "
                   "produced a failure verdict, so the gate proved "
                   "nothing"))
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))

    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=handovers,
        operator_incarnations=incarnations,
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed)
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


@dataclass
class ReconfigChaosConfig(ChaosConfig):
    """Knobs of one reconfiguration soak episode.

    Defaults trade horizon for ladder speed: the victims must walk the
    FULL give-up path (grace → restart rung timeout → reboot rung
    timeout → condemned) before the remap even starts, so the ladder
    timeouts are tightened rather than the horizon stretched."""

    #: Permanent node kills, spread across >= 2 distinct slices.
    kills: int = 2
    #: Hot-standby spares seeded next to the fleet (>= kills proves the
    #: full-remap outcome; fewer exercises degraded admissions).
    spares: int = 2

    def remediation_policy(self) -> RemediationPolicySpec:
        policy = RemediationPolicySpec(
            enable=True,
            max_concurrent=2,
            max_unavailable="50%",
            restart_attempts=1,
            max_attempts=2,
            action_timeout_seconds=120,
            settle_seconds=30,
            revalidate_timeout_seconds=300,
            drain=DrainSpec(enable=True, force=True,
                            timeout_seconds=240),
            reconfiguration=ReconfigurationPolicySpec(
                enable=True,
                spare_provision_timeout_seconds=6000,
                settle_seconds=60,
                allow_degraded=True,
                take_over_failed_upgrades=True))
        policy.detection.not_ready_grace_seconds = 60
        return policy

    def upgrade_policy(self) -> UpgradePolicySpec:
        # slice-atomic planning with the multislice constraint live:
        # the gate must prove the constraint follows the remap instead
        # of double-counting old+new members
        return UpgradePolicySpec(
            auto_upgrade=True,
            max_parallel_upgrades=0,
            max_unavailable="50%",
            topology_mode="slice",
            max_unavailable_slices_per_job=1,
            drain=DrainSpec(enable=True, force=True,
                            timeout_seconds=300),
            capacity=CapacityBudgetSpec(enable=True))


def _restore_workload_pods_by_pool(cluster: FakeCluster,
                                   fleet: FleetSpec,
                                   topology_keys: TopologyKeys) -> None:
    """Pool-membership-based JobSet stand-in for reconfig episodes.

    simulate.restore_workload_pods addresses hosts by their ORIGINAL
    names (``s<slice>-h<host>``), which goes stale the moment a remap
    swaps a spare in. This variant derives each member slice's hosts
    from the nodepool label and recreates the job replica once the
    slice is whole — full shape, or its documented degraded shape —
    and every current member is schedulable + Ready.
    """
    from tpu_operator_libs.simulate import JOBSET_NAME_LABEL
    from tpu_operator_libs.topology.slice_topology import (
        decode_degraded_slices,
    )

    if not fleet.multislice_jobs:
        return
    nodes = cluster.list_nodes()
    by_pool: dict[str, list] = {}
    for node in nodes:
        pool = node.metadata.labels.get(GKE_NODEPOOL_LABEL)
        if pool:
            by_pool.setdefault(pool, []).append(node)
    lost: dict[str, tuple[str, ...]] = {}
    for ds in cluster.list_daemon_sets(NS):
        lost.update(decode_degraded_slices(ds.metadata.annotations.get(
            topology_keys.degraded_slices_annotation, "")))
    existing = {p.metadata.name
                for p in cluster.list_pods(namespace=WORKLOAD_NS)}
    from tpu_operator_libs.k8s.objects import (
        ContainerStatus,
        ObjectMeta,
        Pod,
        PodPhase,
        PodSpec,
        PodStatus,
    )

    for job, slice_ids in fleet.multislice_jobs:
        for s in slice_ids:
            pod_name = f"{job}-s{s}"
            if pod_name in existing:
                continue
            pool = f"pool-{s}"
            members = sorted(by_pool.get(pool, []),
                             key=lambda n: n.metadata.name)
            expected = fleet.hosts_per_slice - len(lost.get(pool, ()))
            if len(members) < expected or expected <= 0:
                continue  # slice still short of its (declared) shape
            if any(n.is_unschedulable() or not n.is_ready()
                   for n in members):
                continue  # replica stays Pending until the slice is back
            cluster.add_pod(Pod(
                metadata=ObjectMeta(
                    name=pod_name, namespace=WORKLOAD_NS,
                    labels={JOBSET_NAME_LABEL: job}),
                spec=PodSpec(node_name=members[0].metadata.name),
                status=PodStatus(
                    phase=PodPhase.RUNNING,
                    container_statuses=[
                        ContainerStatus(name="worker", ready=True)])))


def run_reconfig_soak(seed: int,
                      config: Optional[ReconfigChaosConfig] = None,
                      ) -> ChaosReport:
    """The degraded-slice reconfiguration gate: k nodes are killed
    permanently across >= 2 slices mid-rollout (plus operator crashes
    and control-plane faults), and the system must route every affected
    slice around its dead host instead of parking it.

    What the episode proves, via the monitor's invariants plus the
    convergence check:

    - every multislice job holds a legal placement at every observed
      step — each member slice is full, actively being disrupted under
      budget, or DECLARED degraded; never silently short
      (``slice-placement``);
    - with spares available, each affected slice is remapped: the spare
      is upgraded to the target revision while still out of the slice
      and is never cordoned again after joining — zero extra
      cordon/drain cycles versus the joint plan
      (``reconfig-joint-plan``);
    - condemned nodes end parked in remediation-failed, released from
      their pools, with the ``NodeCondemned`` record stamped, and every
      surviving + spare host converges to upgrade-done on the final
      revision.

    Deterministic in ``seed``; time-to-remapped samples ride the report
    trace (and ``monitor.remap_seconds``).
    """
    config = config or ReconfigChaosConfig()
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay,
        multislice_jobs=(
            ("chaos-job", tuple(range(config.n_slices))),))
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    topo_keys = TopologyKeys(driver=keys.driver, domain=keys.domain)
    seed_spare_pool(cluster, fleet, config.spares)
    node_names = [n.metadata.name for n in cluster.list_nodes()]

    slice_members: dict[str, list[str]] = {}
    for node in cluster.list_nodes():
        pool = node.metadata.labels.get(GKE_NODEPOOL_LABEL)
        if pool:
            slice_members.setdefault(pool, []).append(node.metadata.name)
    schedule = FaultSchedule.generate_reconfig(
        seed, slice_members, horizon=config.horizon, kills=config.kills)
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name)
    injector.install()
    # rollout #2 mid-horizon, exactly like the main soak: kills land on
    # a mid-rollout fleet and spares must chase the FINAL revision
    cluster.schedule_at(
        config.horizon / 2.0,
        lambda: cluster.bump_daemon_set_revision(NS, "libtpu",
                                                 FINAL_REVISION))

    upgrade_policy = config.upgrade_policy()
    remediation_policy = config.remediation_policy()
    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        # slice planner may legally overdraw; the placement/joint-plan
        # invariants are this gate's teeth
        max_unavailable=None,
        remediation_max_unavailable=None,
        max_parallel_upgrades=0,
        reconfig=ReconfigExpectation(
            topology_keys=topo_keys,
            target_revision=FINAL_REVISION,
            runtime_namespace=NS))

    incarnations = 1
    handovers = 0
    reconciles = 0
    op = _OperatorIncarnation(cluster, clock, keys, rem_keys, config,
                              injector, identity="operator-1",
                              with_reconfigurer=True, monitor=monitor)

    def next_incarnation(reason: str) -> _OperatorIncarnation:
        nonlocal incarnations
        incarnations += 1
        injector.fuse.reset()
        monitor.trace.append(
            f"[t={clock.now():g}] operator restart #{incarnations} "
            f"({reason}) — rebuilding managers from cluster state alone")
        return _OperatorIncarnation(
            cluster, clock, keys, rem_keys, config, injector,
            identity=f"operator-{incarnations}", with_reconfigurer=True,
            monitor=monitor)

    def converged() -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = cluster.list_pods(namespace=NS)
            workload = cluster.list_pods(namespace=WORKLOAD_NS)
            daemon_sets = cluster.list_daemon_sets(NS)
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != len(node_names):
            return False
        pods_by_node: dict[str, list] = {}
        for pod in pods:
            if pod.controller_owner() is not None and pod.spec.node_name:
                pods_by_node.setdefault(pod.spec.node_name, []).append(pod)
        pools: dict[str, list] = {}
        for node in nodes:
            labels = node.metadata.labels
            condemned = rem_keys.condemned_annotation \
                in node.metadata.annotations
            if condemned:
                # parked for repair: quarantined, out of its slice
                if labels.get(rem_keys.state_label) \
                        != str(RemediationState.FAILED):
                    return False
                if not node.is_unschedulable():
                    return False
                if labels.get(GKE_NODEPOOL_LABEL):
                    return False
                continue
            if labels.get(keys.state_label) != str(UpgradeState.DONE):
                return False
            if labels.get(rem_keys.state_label, ""):
                return False
            if keys.skip_label in labels:
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
            runtime = pods_by_node.get(node.metadata.name, [])
            if not any(
                    p.metadata.labels.get(
                        POD_CONTROLLER_REVISION_HASH_LABEL)
                    == FINAL_REVISION and p.is_ready() for p in runtime):
                return False
            pool = labels.get(GKE_NODEPOOL_LABEL)
            if pool:
                pools.setdefault(pool, []).append(node)
        # every slice back to full shape (enough spares were seeded for
        # every kill, so no degraded entry may survive convergence)
        for s in range(config.n_slices):
            if len(pools.get(f"pool-{s}", [])) != fleet.hosts_per_slice:
                return False
        if config.spares >= config.kills and any(
                topo_keys.degraded_slices_annotation
                in ds.metadata.annotations for ds in daemon_sets):
            return False
        # every multislice job replica rescheduled
        names = {p.metadata.name for p in workload}
        for job, slice_ids in fleet.multislice_jobs:
            if any(f"{job}-s{s}" not in names for s in slice_ids):
                return False
        return True

    steps = 0
    is_converged = False
    quiesce_ticks = 0
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        was_leading = op.elector.is_leader
        op.elector.try_acquire_or_renew()
        if was_leading and not op.elector.is_leader:
            handovers += 1
            op = next_incarnation("leader election lost")
            op.elector.try_acquire_or_renew()
        if op.elector.is_leader:
            injector.arm_due_crashes(now)
            op.nudger.pop_due(now)
            op.nudger.consume_pending()
            try:
                op.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                         remediation_policy)
                op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                     upgrade_policy)
                reconciles += 1
            except OperatorCrash:
                op = next_incarnation("operator crash mid-reconcile")
            except BuildStateError:
                pass
            except (ApiServerError, ConflictError, NotFoundError):
                pass
            if injector.fuse.pending:
                op = next_incarnation("operator crash (surfaced late)")
        monitor.drain()
        try:
            _restore_workload_pods_by_pool(cluster, fleet, topo_keys)
        except (ApiServerError, TimeoutError):
            pass
        monitor.drain()
        if (now > schedule.last_fault_time
                and not injector.fuse.armed
                and not injector.fuse.pending
                and converged()):
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    if is_converged:
        monitor.final_check()
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"fleet did not converge (slices remapped, survivors "
                   f"on {FINAL_REVISION!r}, condemned nodes parked) "
                   f"within {config.max_steps} steps "
                   f"({clock.now():g}s virtual)"))

    # harness sanity: the episode must have exercised what it gates
    if injector.nodes_killed < 2:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail=f"only {injector.nodes_killed} node kill(s) fired; "
                   f"the gate requires kills across >= 2 slices"))
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))
    if is_converged and len(monitor.remap_seconds) < injector.nodes_killed:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="monitor",
            detail=f"only {len(monitor.remap_seconds)} condemned→released "
                   f"remap(s) observed for {injector.nodes_killed} "
                   f"kill(s) — a slice was not routed around its dead "
                   f"host"))
    if monitor.remap_seconds:
        monitor.trace.append(
            f"[t={clock.now():g}] time-to-remapped (condemned→released, "
            f"s): {sorted(round(s, 1) for s in monitor.remap_seconds)}")

    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=handovers,
        operator_incarnations=incarnations,
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed)
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


@dataclass
class PrecursorChaosConfig(ReconfigChaosConfig):
    """Knobs of one predictive-health (condemn-before-fail) episode.

    The fleet shape and reconfiguration ladder are the reconfig gate's;
    on top of them a classless serving sim replays a diurnal trace so
    "unplanned workload drop" is measured in SESSIONS, per id, and the
    degradation→death schedule gives the precursor model a generous
    observation lead before each seeded kill."""

    #: False = the reactive-only baseline: the same fleet, schedule and
    #: serving trace, but the precursor arc is disabled — every victim
    #: pays the full WedgeDetector→ladder→condemn MTTR. The precursor
    #: bench runs both modes and diffs the outcome.
    precursor_enable: bool = True
    rate_threshold_per_hour: float = 6.0
    min_observations: int = 3
    #: Fleet-wide at-risk budget. 50% of the 8-node default fleet = 4:
    #: both victims condemn concurrently with headroom to prove the
    #: budget is a cap, not a serializer.
    max_at_risk: IntOrString = "50%"
    per_node_capacity: int = 4
    #: Short generations: an at-risk drain quiesces within a few ticks,
    #: keeping the planned-drain window comfortably inside the
    #: ramp→kill lead on every seed.
    generation_seconds: "tuple[float, float]" = (10.0, 25.0)
    diurnal_period: float = 600.0
    trough_util: float = 0.3
    peak_util: float = 0.55

    def remediation_policy(self) -> RemediationPolicySpec:
        policy = super().remediation_policy()
        policy.precursor = PrecursorPolicySpec(
            enable=self.precursor_enable,
            max_at_risk=self.max_at_risk,
            rate_threshold_per_hour=self.rate_threshold_per_hour,
            min_observations=self.min_observations)
        return policy

    def upgrade_policy(self) -> UpgradePolicySpec:
        policy = super().upgrade_policy()
        # The serving sim here feeds the at-risk DRAIN gate, not the
        # budget: with the capacity controller live, two permanently
        # parked victims would pin "unavailable" above the shrunken
        # effective budget and starve the rollout forever. The budget
        # modulation gates are the budget/handover soaks' job.
        policy.capacity = CapacityBudgetSpec(enable=False)
        return policy


#: Annotation-key substrings excluded from the final-state fingerprint:
#: the precursor's own stamps (``-precursor.``, ``at-risk``) plus all
#: three arcs' bookkeeping — remediation stamps, learned upgrade
#: telemetry, and the reconfigurer's remap audit trail
#: (``-topology.``) — which legitimately differ between a predictive
#: and a reactive walk of the same episode. What remains — labels,
#: pools, schedulability, readiness, upgrade state — must be
#: BIT-IDENTICAL between the two modes.
_FINGERPRINT_EXCLUDED = ("-precursor.", "-remediation.", "-upgrade.",
                         "-topology.", "-fsck.")


def _fleet_fingerprint(cluster: FakeCluster,
                       fungible: "frozenset[str]" = frozenset(),
                       ) -> "list[tuple]":
    """Canonical final-cluster-state digest for the precursor bench's
    bit-identical check (modulo the excluded annotation namespaces).

    ``fungible`` names the seeded hot spares, identical by
    construction: WHICH spare backfilled which slice is
    condemnation-order scheduling noise (the predictive walk condemns
    in verdict order, the reactive one in kill order), so their
    nodepool label is lifted out of the per-node tuple and folded into
    a pool-composition digest instead — each pool must still end up
    with the same surviving members plus the same number of spare
    backfills.
    """
    out = []
    pools: "dict[str, tuple[list[str], list[int]]]" = {}
    for node in sorted(cluster.list_nodes(),
                       key=lambda n: n.metadata.name):
        name = node.metadata.name
        labels = dict(node.metadata.labels)
        pool = labels.get(GKE_NODEPOOL_LABEL)
        if pool:
            fixed, spare_count = pools.setdefault(pool, ([], [0]))
            if name in fungible:
                labels.pop(GKE_NODEPOOL_LABEL)
                spare_count[0] += 1
            else:
                fixed.append(name)
        annotations = tuple(sorted(
            (k, v) for k, v in node.metadata.annotations.items()
            if not any(sub in k for sub in _FINGERPRINT_EXCLUDED)))
        out.append((name, tuple(sorted(labels.items())),
                    node.is_unschedulable(), node.is_ready(),
                    annotations))
    out.append(("~pools", tuple(sorted(
        (pool, tuple(sorted(fixed)), spare_count[0])
        for pool, (fixed, spare_count) in pools.items()))))
    return out


def run_precursor_soak(seed: int,
                       config: Optional[PrecursorChaosConfig] = None,
                       ) -> ChaosReport:
    """The condemn-before-fail gate: every seeded node kill is preceded
    by a hardware-degradation counter ramp on the same node, and the
    FailurePrecursorModel must route the slice around the dying host —
    at-risk verdict, spare remapped, planned serving-gated drain —
    BEFORE the kill lands, under operator crashes and control-plane
    faults.

    What the episode proves, via the monitor's invariants plus the
    runner's own checks (the always-on predictive invariants; all
    skipped in the reactive baseline mode):

    - **condemn-before-fail**: with a spare available, an at-risk
      node's slice takes ZERO downtime — at the moment its seeded kill
      lands the victim is already out of the pool and its spare serves
      in its place (per-victim downtime sampled every tick);
    - **no unplanned drop**: not one serving session was dropped, by
      fault OR operator, checked per session id — the planned drain
      quiesced the victim's endpoint before eviction and the kill hit
      an empty node;
    - **predictive attribution**: every parked victim carries the
      at-risk stamp from the PRECURSOR verdict (reason
      ``precursor-<signal>:...``), placed >= minObservations reconcile
      ticks before the kill — the reactive ladder never ran;
    - plus the reconfig gate's standing invariants (slice placement,
      joint-plan, legal transitions) and full convergence with every
      victim parked condemned and every slice back to full shape.

    Deterministic in ``seed``. The report's ``stats`` carry the bench
    feed: per-victim downtime, serving drop attribution, and the
    final-state fingerprint (modulo per-arc bookkeeping annotations).
    """
    config = config or PrecursorChaosConfig()
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay,
        multislice_jobs=(
            ("chaos-job", tuple(range(config.n_slices))),))
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    topo_keys = TopologyKeys(driver=keys.driver, domain=keys.domain)
    spare_names = seed_spare_pool(cluster, fleet, config.spares)
    node_names = [n.metadata.name for n in cluster.list_nodes()]

    slice_members: dict[str, list[str]] = {}
    for node in cluster.list_nodes():
        pool = node.metadata.labels.get(GKE_NODEPOOL_LABEL)
        if pool:
            slice_members.setdefault(pool, []).append(node.metadata.name)
    pool_of = {name: pool for pool, members in slice_members.items()
               for name in members}
    schedule = FaultSchedule.generate_precursor(
        seed, slice_members, horizon=config.horizon, kills=config.kills)
    #: victim -> seeded kill time (the downtime/lead anchors).
    kill_at = {e.target: e.at for e in schedule.events
               if e.kind == FAULT_NODE_KILL}
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name)
    injector.install()
    # rollout #2 EARLY, not mid-horizon: predictive remaps start as
    # soon as a verdict streak holds (well before horizon/2), and the
    # joint-plan invariant demands every spare join on the FINAL
    # revision — so the final target must be declared before the first
    # ramp opens. Write traffic deep into the crash window comes from
    # the precursor's own durable stamps (seed annotations ride every
    # observation pass while a ramp is ticking).
    cluster.schedule_at(
        config.horizon * 0.04,
        lambda: cluster.bump_daemon_set_revision(NS, "libtpu",
                                                 FINAL_REVISION))

    trace = DiurnalTrace(seed=seed,
                         period_seconds=config.diurnal_period,
                         trough_util=config.trough_util,
                         peak_util=config.peak_util)
    serving = ServingFleetSim(
        cluster, node_names, trace,
        per_node_capacity=config.per_node_capacity,
        generation_seconds=config.generation_seconds, seed=seed)

    upgrade_policy = config.upgrade_policy()
    remediation_policy = config.remediation_policy()
    predictive = bool(remediation_policy.precursor
                      and remediation_policy.precursor.enable)
    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        max_unavailable=None,
        remediation_max_unavailable=None,
        max_parallel_upgrades=0,
        reconfig=ReconfigExpectation(
            topology_keys=topo_keys,
            target_revision=FINAL_REVISION,
            runtime_namespace=NS))

    incarnations = 1
    handovers = 0
    reconciles = 0
    op = _OperatorIncarnation(cluster, clock, keys, rem_keys, config,
                              injector, identity="operator-1",
                              with_reconfigurer=True, serving=serving,
                              monitor=monitor,
                              precursor_source=injector.health_source)

    def next_incarnation(reason: str) -> _OperatorIncarnation:
        nonlocal incarnations
        incarnations += 1
        injector.fuse.reset()
        monitor.trace.append(
            f"[t={clock.now():g}] operator restart #{incarnations} "
            f"({reason}) — rebuilding managers from cluster state alone")
        return _OperatorIncarnation(
            cluster, clock, keys, rem_keys, config, injector,
            identity=f"operator-{incarnations}", with_reconfigurer=True,
            serving=serving, monitor=monitor,
            precursor_source=injector.health_source)

    def converged() -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = cluster.list_pods(namespace=NS)
            workload = cluster.list_pods(namespace=WORKLOAD_NS)
            daemon_sets = cluster.list_daemon_sets(NS)
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != len(node_names):
            return False
        pods_by_node: dict[str, list] = {}
        for pod in pods:
            if pod.controller_owner() is not None and pod.spec.node_name:
                pods_by_node.setdefault(pod.spec.node_name, []).append(pod)
        pools: dict[str, list] = {}
        parked = 0
        for node in nodes:
            labels = node.metadata.labels
            condemned = rem_keys.condemned_annotation \
                in node.metadata.annotations
            if condemned:
                parked += 1
                if labels.get(rem_keys.state_label) \
                        != str(RemediationState.FAILED):
                    return False
                if not node.is_unschedulable():
                    return False
                if labels.get(GKE_NODEPOOL_LABEL):
                    return False
                continue
            if labels.get(keys.state_label) != str(UpgradeState.DONE):
                return False
            if labels.get(rem_keys.state_label, ""):
                return False
            if keys.skip_label in labels:
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
            runtime = pods_by_node.get(node.metadata.name, [])
            if not any(
                    p.metadata.labels.get(
                        POD_CONTROLLER_REVISION_HASH_LABEL)
                    == FINAL_REVISION and p.is_ready() for p in runtime):
                return False
            pool = labels.get(GKE_NODEPOOL_LABEL)
            if pool:
                pools.setdefault(pool, []).append(node)
        for s in range(config.n_slices):
            if len(pools.get(f"pool-{s}", [])) != fleet.hosts_per_slice:
                return False
        if config.spares >= config.kills and any(
                topo_keys.degraded_slices_annotation
                in ds.metadata.annotations for ds in daemon_sets):
            return False
        names = {p.metadata.name for p in workload}
        for job, slice_ids in fleet.multislice_jobs:
            if any(f"{job}-s{s}" not in names for s in slice_ids):
                return False
        # the serving fleet must be whole again: one live admitting
        # endpoint per surviving node (parked victims serve nothing)
        return (len(serving.endpoints) == len(node_names) - parked
                and not any(ep.draining
                            for ep in serving.endpoints.values()))

    #: victim -> seconds its slice was short a Ready member AFTER the
    #: seeded kill (tick-sampled): the gate's MTTR measure. Predictive
    #: mode must hold it at zero; the reactive baseline pays the full
    #: ladder walk here.
    downtime: dict[str, float] = {name: 0.0 for name in kill_at}

    def sample_downtime(now: float) -> None:
        try:
            nodes = cluster.list_nodes()
        except (ApiServerError, TimeoutError):
            return
        by_pool: dict[str, list] = {}
        for node in nodes:
            pool = node.metadata.labels.get(GKE_NODEPOOL_LABEL)
            if pool:
                by_pool.setdefault(pool, []).append(node)
        for victim, at in kill_at.items():
            if now < at:
                continue
            ready = [n for n in by_pool.get(pool_of[victim], [])
                     if n.is_ready()]
            if len(ready) < fleet.hosts_per_slice:
                downtime[victim] += config.reconcile_interval

    steps = 0
    is_converged = False
    quiesce_ticks = 0
    serving.tick(clock.now())
    monitor.drain()
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        was_leading = op.elector.is_leader
        op.elector.try_acquire_or_renew()
        if was_leading and not op.elector.is_leader:
            handovers += 1
            op = next_incarnation("leader election lost")
            op.elector.try_acquire_or_renew()
        if op.elector.is_leader:
            injector.arm_due_crashes(now)
            op.nudger.pop_due(now)
            op.nudger.consume_pending()
            try:
                op.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                         remediation_policy)
                op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                     upgrade_policy)
                reconciles += 1
            except OperatorCrash:
                op = next_incarnation("operator crash mid-reconcile")
            except BuildStateError:
                pass
            except (ApiServerError, ConflictError, NotFoundError):
                pass
            if injector.fuse.pending:
                op = next_incarnation("operator crash (surfaced late)")
        monitor.drain()
        try:
            _restore_workload_pods_by_pool(cluster, fleet, topo_keys)
        except (ApiServerError, TimeoutError):
            pass
        serving.tick(now)
        monitor.drain()
        sample_downtime(now)
        if (now > schedule.last_fault_time
                and not injector.fuse.armed
                and not injector.fuse.pending
                and converged()):
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    if is_converged:
        monitor.final_check()
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"fleet did not converge (victims parked, slices "
                   f"remapped, survivors on {FINAL_REVISION!r}) within "
                   f"{config.max_steps} steps "
                   f"({clock.now():g}s virtual)"))

    spare_backed = config.spares >= config.kills
    lead_seconds: dict[str, float] = {}
    try:
        final_nodes = {n.metadata.name: n for n in cluster.list_nodes()}
    except (ApiServerError, TimeoutError):
        final_nodes = {}
    if predictive and spare_backed:
        # no unplanned drop, per SESSION: the seed-pure ids make the
        # attribution exact — one dropped session is a named violation
        for record in serving.drop_records:
            monitor.violations.append(InvariantViolation(
                invariant="predictive-drop", at=record["at"],
                subject=record["session"],
                detail=f"session {record['session']} was dropped "
                       f"(cause: {record['cause']}) — an at-risk node "
                       f"with an available spare took an unplanned "
                       f"workload drop"))
        for victim, at in sorted(kill_at.items()):
            node = final_nodes.get(victim)
            stamp = (node.metadata.annotations.get(
                rem_keys.at_risk_annotation) if node else None)
            reason = (node.metadata.annotations.get(
                rem_keys.at_risk_reason_annotation, "")
                if node else "")
            if stamp is None:
                monitor.violations.append(InvariantViolation(
                    invariant="condemn-before-fail", at=clock.now(),
                    subject=victim,
                    detail="victim carries no at-risk stamp — the "
                           "precursor never condemned it (the "
                           "reactive ladder paid the MTTR instead)"))
                continue
            lead = at - float(int(stamp))
            lead_seconds[victim] = lead
            min_lead = (config.min_observations
                        * config.reconcile_interval)
            if lead < min_lead:
                monitor.violations.append(InvariantViolation(
                    invariant="condemn-before-fail", at=at,
                    subject=victim,
                    detail=f"at-risk verdict landed only {lead:g}s "
                           f"before the kill (< {min_lead:g}s = "
                           f"minObservations ticks)"))
            if not reason.startswith("precursor-"):
                monitor.violations.append(InvariantViolation(
                    invariant="condemn-before-fail", at=at,
                    subject=victim,
                    detail=f"at-risk reason {reason!r} is not a "
                           f"precursor verdict"))
            if downtime.get(victim, 0.0) > 0.0:
                monitor.violations.append(InvariantViolation(
                    invariant="condemn-before-fail", at=at,
                    subject=victim,
                    detail=f"slice {pool_of[victim]} was short a Ready "
                           f"member for {downtime[victim]:g}s after "
                           f"the seeded kill — the remap did not "
                           f"complete before the hardware died"))
        if injector.degradation_ticks == 0:
            monitor.violations.append(InvariantViolation(
                invariant="harness", at=clock.now(), subject="injector",
                detail="no degradation tick ever fired — the precursor "
                       "had nothing to observe, so the gate proved "
                       "nothing"))
    # harness sanity shared with the reconfig gate
    if injector.nodes_killed < 2:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail=f"only {injector.nodes_killed} node kill(s) fired; "
                   f"the gate requires kills across >= 2 slices"))
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))
    if is_converged and len(monitor.remap_seconds) < injector.nodes_killed:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="monitor",
            detail=f"only {len(monitor.remap_seconds)} remap(s) "
                   f"observed for {injector.nodes_killed} kill(s) — a "
                   f"slice was not routed around its dying host"))

    monitor.trace.append(
        f"[t={clock.now():g}] precursor({'on' if predictive else 'off'})"
        f": victim downtime (s) "
        f"{ {v: round(s, 1) for v, s in sorted(downtime.items())} }; "
        f"at-risk lead (s) "
        f"{ {v: round(s, 1) for v, s in sorted(lead_seconds.items())} }; "
        f"{injector.degradation_ticks} degradation tick(s); serving "
        f"{serving.summary()}")

    try:
        fingerprint = _fleet_fingerprint(
            cluster, fungible=frozenset(spare_names))
    except (ApiServerError, TimeoutError):
        fingerprint = []
    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=handovers,
        operator_incarnations=incarnations,
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed,
        stats={
            "precursorEnabled": predictive,
            "victimDowntimeSeconds": dict(sorted(downtime.items())),
            "atRiskLeadSeconds": dict(sorted(lead_seconds.items())),
            "remapSeconds": sorted(
                round(s, 1) for s in monitor.remap_seconds),
            "serving": serving.summary(),
            "degradationTicks": injector.degradation_ticks,
            "fingerprint": fingerprint,
        })
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


@dataclass
class FsckChaosConfig(ChaosConfig):
    """Knobs of one durable-state fsck episode.

    The fleet and rollout shape are the base chaos gate's; the schedule
    swaps the sampled side-fault pool for 4-8 seeded
    ``state-corruption`` events (plus crashes and api/watch faults).
    Each seed is run TWICE — corrupted and corruption-free twin — and
    the converged fleets must fingerprint bit-identically."""

    #: Side fault kinds beside crashes + corruption (api-burst /
    #: watch-break; the generator excludes stale-reads by design).
    extra_fault_kinds: int = 2


def _run_fsck_episode(seed: int, config: FsckChaosConfig,
                      corrupt: bool) -> ChaosReport:
    """One fsck episode: the base chaos loop with the auditor/janitor
    pair scanning BEFORE the state machines every leader pass.

    The scan-before-act ordering is the gate's no-corrupted-decision
    mechanism: corruption lands between ticks (scheduled cluster
    actions), every leader pass audits first, and a pass with findings
    repairs them and SKIPS the managers — so no manager ever builds
    state from a snapshot containing an unrepaired corrupted stamp.
    Unrepairable findings would hold the managers forever and fail the
    liveness backstop, which is exactly the alarm that should fire.
    """
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay,
        multislice_jobs=(
            ("chaos-job", tuple(range(config.n_slices))),))
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    node_names = [n.metadata.name for n in cluster.list_nodes()]

    schedule = FaultSchedule.generate_fsck(
        seed, node_names, ds_target=f"{NS}/libtpu",
        horizon=config.horizon, extra_kinds=config.extra_fault_kinds)
    if not corrupt:
        # the corruption-free twin: SAME crashes and side faults at the
        # same instants, zero vandalism — the fingerprint baseline
        schedule = schedule.without(FAULT_STATE_CORRUPTION)
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name,
                             upgrade_keys=keys,
                             remediation_keys=rem_keys)
    injector.install()
    cluster.schedule_at(
        config.horizon / 2.0,
        lambda: cluster.bump_daemon_set_revision(NS, "libtpu",
                                                 FINAL_REVISION))

    registry = default_registry(driver=keys.driver, domain=keys.domain)
    # the ONLY fsck state that survives incarnations: audited repairs
    # with their explain() chains
    repair_log: list = []

    upgrade_policy = config.upgrade_policy()
    remediation_policy = config.remediation_policy()
    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        max_unavailable=upgrade_policy.max_unavailable,
        remediation_max_unavailable=remediation_policy.max_unavailable,
        max_parallel_upgrades=config.max_parallel_upgrades)

    incarnations = 1
    handovers = 0
    reconciles = 0
    fsck_hold_ticks = 0
    op = _OperatorIncarnation(cluster, clock, keys, rem_keys, config,
                              injector, identity="operator-1",
                              monitor=monitor, fsck_registry=registry,
                              fsck_repair_log=repair_log)

    def next_incarnation(reason: str) -> _OperatorIncarnation:
        nonlocal incarnations
        incarnations += 1
        injector.fuse.reset()
        monitor.trace.append(
            f"[t={clock.now():g}] operator restart #{incarnations} "
            f"({reason}) — rebuilding managers from cluster state alone")
        return _OperatorIncarnation(
            cluster, clock, keys, rem_keys, config, injector,
            identity=f"operator-{incarnations}", monitor=monitor,
            fsck_registry=registry, fsck_repair_log=repair_log)

    def converged() -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = cluster.list_pods(namespace=NS)
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != len(node_names):
            return False
        for node in nodes:
            labels = node.metadata.labels
            if labels.get(keys.state_label) != str(UpgradeState.DONE):
                return False
            if labels.get(rem_keys.state_label, ""):
                return False
            if keys.skip_label in labels:
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
        runtime = [p for p in pods
                   if p.controller_owner() is not None]
        if len(runtime) != len(node_names):
            return False
        return all(
            p.metadata.labels.get(POD_CONTROLLER_REVISION_HASH_LABEL)
            == FINAL_REVISION and p.is_ready() for p in runtime)

    steps = 0
    is_converged = False
    quiesce_ticks = 0
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        was_leading = op.elector.is_leader
        op.elector.try_acquire_or_renew()
        if was_leading and not op.elector.is_leader:
            handovers += 1
            op = next_incarnation("leader election lost")
            op.elector.try_acquire_or_renew()
        if op.elector.is_leader:
            injector.arm_due_crashes(now)
            op.nudger.pop_due(now)
            op.nudger.consume_pending()
            try:
                # fsck runs FIRST: a pass that finds corruption repairs
                # it and holds the machines this tick, so no corrupted
                # stamp is ever in a snapshot a manager acts on
                findings = op.auditor.scan(
                    cluster.list_nodes(),
                    cluster.list_daemon_sets(NS))
                if findings:
                    fsck_hold_ticks += 1
                    monitor.trace.append(
                        f"[t={now:g}] fsck: {len(findings)} finding(s) "
                        f"— repairing, managers held this pass")
                    op.janitor.repair(findings)
                else:
                    op.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                             remediation_policy)
                    op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                         upgrade_policy)
                    reconciles += 1
            except OperatorCrash:
                op = next_incarnation("operator crash mid-reconcile")
            except BuildStateError:
                pass  # incomplete snapshot; next tick retries
            except (ApiServerError, ConflictError, NotFoundError):
                pass  # pass aborted on a transient; next tick retries
            if injector.fuse.pending:
                op = next_incarnation("operator crash (surfaced late)")
        monitor.drain()
        if steps % 5 == 0 and op.upgrade.last_state is not None:
            for parked in monitor.parked_nodes():
                monitor.audit_explain(parked,
                                      op.upgrade.explain(parked))
        try:
            restore_workload_pods(cluster, fleet)
        except (ApiServerError, TimeoutError):
            pass  # injected fault; the JobSet controller retries too
        monitor.drain()
        if (now > schedule.last_fault_time
                and not injector.fuse.armed
                and not injector.fuse.pending
                and converged()):
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    if is_converged:
        monitor.final_check()
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"fleet did not converge within {config.max_steps} "
                   f"steps ({clock.now():g}s virtual) after the last "
                   f"fault healed at {schedule.last_fault_time:g}s"))

    # fsck-clean: a FRESH auditor (no warm digest cache) over the final
    # fleet must find nothing — every injected corruption and every
    # crash-torn repair has been healed
    try:
        leftover = StateAuditor(registry).scan(
            consume_transient(cluster.list_nodes),
            consume_transient(lambda: cluster.list_daemon_sets(NS)))
    except (ApiServerError, TimeoutError, RuntimeError):
        leftover = []
        monitor.violations.append(InvariantViolation(
            invariant="fsck-clean", at=clock.now(), subject="fleet",
            detail="final fsck scan could not read the fleet"))
    for f in leftover:
        monitor.violations.append(InvariantViolation(
            invariant="fsck-clean", at=clock.now(),
            subject=f"{f.target_kind}/{f.target}",
            detail=f"post-soak stamp {f.key}={f.value!r} still "
                   f"classified {f.classification}: {f.reason}"))

    # repair coverage: every landed corruption must be matched by an
    # audited repair of the same (target, key) at or after injection
    for rec in injector.corruptions:
        if not any(r.target == rec.target and r.key == rec.key
                   and r.at >= rec.at for r in repair_log):
            monitor.violations.append(InvariantViolation(
                invariant="fsck-repair-coverage", at=rec.at,
                subject=f"{rec.target_kind}/{rec.target}",
                detail=f"corruption of {rec.key} (mode {rec.mode}, "
                       f"value {rec.value!r}) was never repaired"))
    # every repair audited with a non-empty explain chain
    for r in repair_log:
        if not r.chain:
            monitor.violations.append(InvariantViolation(
                invariant="fsck-audit", at=r.at,
                subject=f"{r.target_kind}/{r.target}",
                detail=f"repair {r.action} of {r.key} carries no "
                       f"explain() chain"))

    # harness sanity: the corrupted episode must actually have vandals
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))
    if corrupt and len(injector.corruptions) < 3:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail=f"only {len(injector.corruptions)} corruption(s) "
                   f"landed — the fsck gate needs a real vandal"))

    try:
        fingerprint = _fleet_fingerprint(cluster)
    except (ApiServerError, TimeoutError):
        fingerprint = []
    repairs_by_action: dict = {}
    for r in repair_log:
        repairs_by_action[r.action] = (
            repairs_by_action.get(r.action, 0) + 1)
    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=handovers,
        operator_incarnations=incarnations,
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed,
        stats={
            "corrupted": corrupt,
            "corruptionsInjected": len(injector.corruptions),
            "corruptionModes": sorted(
                {rec.mode for rec in injector.corruptions}),
            "repairsByAction": dict(sorted(repairs_by_action.items())),
            "fsckHoldTicks": fsck_hold_ticks,
            "fingerprint": fingerprint,
        })
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    return report


def run_fsck_soak(seed: int,
                  config: Optional[FsckChaosConfig] = None,
                  ) -> ChaosReport:
    """The durable-state fsck gate: one seeded episode run twice.

    The corrupted run takes the full ``generate_fsck`` schedule — 4-8
    external-writer corruption events laid over crashes and API faults
    mid-rollout; the baseline twin strips ONLY the corruption (same
    seed, same crash instants). The corrupted run must (1) converge,
    (2) end fsck-clean with every corruption matched by an audited
    repair carrying a non-empty explain() chain, and (3) produce a
    final fleet fingerprint BIT-IDENTICAL to the baseline's — the
    vandalism leaves no trace the repairs didn't erase. Baseline
    violations are folded into the returned report (prefixed
    ``baseline:``), so a broken twin can never green the gate.
    """
    config = config or FsckChaosConfig()
    report = _run_fsck_episode(seed, config, corrupt=True)
    baseline = _run_fsck_episode(seed, config, corrupt=False)

    for violation in baseline.violations:
        report.violations.append(InvariantViolation(
            invariant=violation.invariant, at=violation.at,
            subject=f"baseline:{violation.subject}",
            detail=violation.detail))
    if not baseline.converged:
        report.converged = False
    fingerprint = report.stats.get("fingerprint")
    baseline_fp = baseline.stats.get("fingerprint")
    if fingerprint != baseline_fp:
        diff = [f"corrupted={c!r} baseline={b!r}"
                for c, b in zip(fingerprint or [], baseline_fp or [])
                if c != b]
        report.violations.append(InvariantViolation(
            invariant="fsck-fingerprint", at=report.total_seconds,
            subject="fleet",
            detail="corrupted-run fleet fingerprint diverges from the "
                   "corruption-free twin: "
                   + ("; ".join(diff[:3]) if diff else
                      "fingerprint lengths differ")))
    report.stats["baselineFingerprint"] = baseline_fp
    report.stats["baselineConverged"] = baseline.converged
    report.trace.append(
        f"fsck soak seed={seed}: "
        f"{report.stats['corruptionsInjected']} corruption(s) over "
        f"modes {report.stats['corruptionModes']}, repairs "
        f"{report.stats['repairsByAction']}, "
        f"{report.stats['fsckHoldTicks']} held pass(es), fingerprint "
        f"{'MATCHES' if fingerprint == baseline_fp else 'DIVERGES'} "
        f"baseline")
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


@dataclass
class ReplicaKillConfig(ChaosConfig):
    """Knobs of one sharded-control-plane (replica-kill) soak episode."""

    #: Operator replicas of the sharded control plane.
    replicas: int = 2
    #: Ring granularity: total shards = replicas * shards_per_replica.
    shards_per_replica: int = 2
    #: Per-shard / member-slot Lease duration (renew deadline 2/3).
    shard_lease_duration: float = 30.0
    #: Max virtual seconds an orphaned shard may go before a live
    #: replica owns it again (the shard-takeover invariant's bound):
    #: member-slot expiry + shard-lease expiry + election rounds + one
    #: composed crash-restart — ~5 lease durations.
    takeover_grace: float = 150.0
    shard_lease_prefix: str = "chaos-shard"

    @property
    def num_shards(self) -> int:
        return self.replicas * self.shards_per_replica


class _ShardAuditClient:
    """Write-attributing FakeCluster wrapper for the replica-kill gate.

    Every durable NODE write a replica issues is audited — at the
    instant of the write, against the server-side shard Lease —
    INDEPENDENTLY of the fencing layer under test: the fence lives in
    the state provider / cordon manager, this wrapper sits below them
    at the client boundary, so a fencing bug shows up as a
    ``shard-ownership`` violation instead of silently passing.
    """

    _AUDITED = ("patch_node_labels", "patch_node_annotations",
                "patch_node_meta", "set_node_unschedulable")

    def __init__(self, cluster: FakeCluster, identity: str,
                 monitor: InvariantMonitor, ring: "object",
                 pools: "dict[str, str]", lease_namespace: str,
                 shard_lease_prefix: str) -> None:
        self._cluster = cluster
        self._identity = identity
        self._monitor = monitor
        self._ring = ring
        self._pools = pools
        self._lease_namespace = lease_namespace
        self._shard_lease_prefix = shard_lease_prefix

    def __getattr__(self, name: str) -> "object":
        return getattr(self._cluster, name)

    def _audit(self, node_name: str) -> None:
        shard = self._ring.shard_for(node_name,
                                     self._pools.get(node_name, ""))
        try:
            lease = self._cluster.get_lease(
                self._lease_namespace,
                f"{self._shard_lease_prefix}-shard-{shard:02d}")
            holder = lease.holder_identity
        except NotFoundError:
            holder = ""
        self._monitor.audit_shard_write(node_name, shard,
                                        self._identity, holder)

    def patch_node_labels(self, name: str, labels: "dict") -> "object":
        self._audit(name)
        return self._cluster.patch_node_labels(name, labels)

    def patch_node_annotations(self, name: str,
                               annotations: "dict") -> "object":
        self._audit(name)
        return self._cluster.patch_node_annotations(name, annotations)

    def patch_node_meta(self, name: str, labels: "dict" = None,
                        annotations: "dict" = None) -> "object":
        self._audit(name)
        return self._cluster.patch_node_meta(name, labels=labels,
                                             annotations=annotations)

    def set_node_unschedulable(self, name: str,
                               unschedulable: bool) -> "object":
        self._audit(name)
        return self._cluster.set_node_unschedulable(name, unschedulable)


class _ShardedReplica:
    """One replica-lifetime of the sharded control plane: fresh
    managers, fresh ShardElector, fresh partition-filtered read cache,
    fresh identity. Everything that survives a kill lives on the
    cluster — the shard/slot Leases, the node labels, the budget-share
    annotations — which is exactly the durability claim the
    replica-kill gate proves. Reads go through the DELTA-WIRED sharded
    path (a ``CachedReadClient`` in deterministic pump mode with the
    elector pushed down as the pod-cache partition filter), so the
    soak gates takeover re-sync correctness: a successor's targeted
    re-LIST + cursor invalidation must reconstruct the dead replica's
    partition from cluster state alone, under the same fault schedule
    that killed it."""

    def __init__(self, cluster: FakeCluster, clock: FakeClock,
                 keys: UpgradeKeys, rem_keys: RemediationKeys,
                 config: ReplicaKillConfig, injector: ChaosInjector,
                 monitor: InvariantMonitor, identity: str,
                 pools: "dict[str, str]") -> None:
        from tpu_operator_libs.k8s.cached import CachedReadClient
        from tpu_operator_libs.k8s.sharding import (
            ShardElectionConfig,
            ShardElector,
        )
        from tpu_operator_libs.upgrade.nudger import ReconcileNudger

        self.identity = identity
        self.nudger = ReconcileNudger(clock=clock)
        self.elector = ShardElector(
            cluster,
            ShardElectionConfig(
                namespace=config.lease_namespace, identity=identity,
                num_shards=config.num_shards, replicas=config.replicas,
                lease_prefix=config.shard_lease_prefix,
                lease_duration=config.shard_lease_duration,
                renew_deadline=config.shard_lease_duration * 2.0 / 3.0,
                retry_period=2.0, renew_jitter=0.0),
            clock=clock)
        audit = _ShardAuditClient(
            cluster, identity, monitor, self.elector.ring, pools,
            config.lease_namespace, config.shard_lease_prefix)
        # The replica's cache sync races the schedule's injected API
        # errors (a real replacement pod's informer start does too):
        # bounded retries, each consuming one injected failure, then
        # let the last error surface to the harness.
        self.cached: "Optional[CachedReadClient]" = None
        for attempt in range(8):
            try:
                self.cached = CachedReadClient(
                    audit, NS, threaded=False, relist_interval=None)
                break
            except Exception:  # noqa: BLE001 — injected API error
                if attempt == 7:
                    raise
        provider = CrashingStateProvider(
            self.cached, keys, None, clock, sync_timeout=5.0,
            poll_interval=1.0, fuse=injector.fuse)
        self.upgrade = ClusterUpgradeStateManager(
            self.cached, keys, clock=clock, async_workers=False,
            provider=provider, poll_interval=1.0, sync_timeout=5.0,
            parallel_workers=config.parallel_workers,
            nudger=self.nudger).with_sharding(self.elector)
        # obs runs live in the sharded gate too: each replica traces
        # its own partition's journeys (trace ids survive takeovers via
        # the durable annotation) and mirrors its decisions into the
        # monitor-held cross-incarnation log
        from tpu_operator_libs.obs import OperatorObservability

        self.obs = OperatorObservability(keys, clock=clock)
        self.upgrade.with_observability(self.obs)
        self.obs.audit.mirror = monitor.note_decision
        monitor.obs_source = lambda: self.obs
        rem_provider = CrashingStateProvider(
            self.cached, rem_keys, None, clock,  # type: ignore[arg-type]
            sync_timeout=5.0, poll_interval=1.0, fuse=injector.fuse)
        self.remediation = NodeRemediationManager(
            self.cached, rem_keys, upgrade_keys=keys, clock=clock,
            provider=rem_provider, poll_interval=1.0, sync_timeout=5.0,
            nudger=self.nudger).with_sharding(self.elector)

    def pump(self) -> None:
        """Apply queued watch events before this tick's reconciles."""
        if self.cached is not None:
            self.cached.pump()

    def stop(self) -> None:
        """Tear down the read cache's watch subscriptions. A killed
        incarnation must stop consuming the broadcaster — its queues
        would otherwise grow for the rest of the episode."""
        if self.cached is not None:
            self.cached.stop()


def run_replica_kill_soak(seed: int,
                          config: Optional[ReplicaKillConfig] = None,
                          ) -> ChaosReport:
    """The sharded-control-plane gate: ≥2 replicas each own a shard
    partition via per-shard Leases, and the schedule kills/deposes them
    mid-wave (SIGKILL without Lease release, shard-Lease steals, an
    operator crash inside the durable-write path, plus control-plane
    faults riding along).

    What the episode proves, via the monitor's invariants plus the
    convergence check:

    - **shard-ownership**: every durable node write that LANDED was
      issued by the replica holding that node's shard Lease at the
      instant of the write (audited below the fencing layer, against
      the server-side Lease) — zero split-brain writes;
    - **budget**: the fleet-wide max-unavailable inequality holds at
      every admission instant, even though no replica ever sees more
      than its own partition — the durable budget shares coordinate
      the joint spend across kills, steals and takeovers;
    - **shard-takeover**: every shard orphaned by a kill is owned by a
      live replica again within ``takeover_grace`` — dead replicas
      stall nothing for longer than a bounded number of lease
      durations;
    - plus the standing legal-transition / workload-placement /
      cordon-pairing invariants, and full convergence: every node
      upgrade-done on the final revision.

    Deterministic in ``seed``.
    """
    config = config or ReplicaKillConfig()
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay,
        multislice_jobs=(
            ("chaos-job", tuple(range(config.n_slices))),))
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    node_names = [n.metadata.name for n in cluster.list_nodes()]
    pools = {n.metadata.name:
             n.metadata.labels.get(GKE_NODEPOOL_LABEL, "")
             for n in cluster.list_nodes()}

    schedule = FaultSchedule.generate_replica_kill(
        seed, node_names, replicas=config.replicas,
        num_shards=config.num_shards, horizon=config.horizon)
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name,
                             shard_lease_prefix=config.shard_lease_prefix)
    injector.install()
    cluster.schedule_at(
        config.horizon / 2.0,
        lambda: cluster.bump_daemon_set_revision(NS, "libtpu",
                                                 FINAL_REVISION))

    upgrade_policy = config.upgrade_policy()
    remediation_policy = config.remediation_policy()
    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        # the budget invariant stays armed FLEET-WIDE: that is the
        # durable-budget-shares proof (remediation budget is enforced
        # per partition, so its global check is disarmed, like the
        # reconfig gate disarms checks it deliberately relaxes)
        max_unavailable=upgrade_policy.max_unavailable,
        remediation_max_unavailable=None,
        max_parallel_upgrades=config.max_parallel_upgrades,
        shard=ShardExpectation(
            num_shards=config.num_shards,
            takeover_grace_seconds=config.takeover_grace))

    generations = [1] * config.replicas
    reconciles = 0
    fencings = 0

    def mk(slot: int) -> _ShardedReplica:
        return _ShardedReplica(
            cluster, clock, keys, rem_keys, config, injector, monitor,
            identity=f"replica-{slot}-{generations[slot]}", pools=pools)

    replicas: "list[Optional[_ShardedReplica]]" = [
        mk(slot) for slot in range(config.replicas)]
    pending_restarts: "list[tuple[float, int]]" = []

    def replace(slot: int, reason: str) -> _ShardedReplica:
        generations[slot] += 1
        injector.fuse.reset()
        fresh = mk(slot)
        monitor.trace.append(
            f"[t={clock.now():g}] replica slot {slot} restart "
            f"#{generations[slot]} ({reason}) — rebuilding from "
            f"cluster state alone")
        return fresh

    def converged() -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = cluster.list_pods(namespace=NS)
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != len(node_names):
            return False
        for node in nodes:
            labels = node.metadata.labels
            if labels.get(keys.state_label) != str(UpgradeState.DONE):
                return False
            if labels.get(rem_keys.state_label, ""):
                return False
            if keys.skip_label in labels:
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
        runtime = [p for p in pods if p.controller_owner() is not None]
        if len(runtime) != len(node_names):
            return False
        return all(
            p.metadata.labels.get(POD_CONTROLLER_REVISION_HASH_LABEL)
            == FINAL_REVISION and p.is_ready() for p in runtime)

    from tpu_operator_libs.k8s.sharding import ShardFencedError

    steps = 0
    is_converged = False
    quiesce_ticks = 0
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        # replica kills: drop the incarnation WITHOUT releasing its
        # Leases; note its shards orphaned for the takeover invariant
        for event in injector.due_replica_kills(now):
            slot = int(event.target)
            victim = replicas[slot]
            if victim is not None:
                for shard in sorted(victim.elector.owned_shards()):
                    monitor.note_shard_orphaned(shard, now)
                monitor.trace.append(
                    f"[t={now:g}] replica {victim.identity} KILLED "
                    f"(slot {slot}; leases NOT released; replacement "
                    f"at t={event.until:g})")
                victim.stop()
                replicas[slot] = None
            if event.until > now:
                pending_restarts.append((event.until, slot))
        due_restarts = [p for p in pending_restarts if p[0] <= now]
        pending_restarts = [p for p in pending_restarts if p[0] > now]
        for _, slot in due_restarts:
            try:
                replicas[slot] = replace(slot, "replacement pod arrived")
            except (ApiServerError, ConflictError, NotFoundError,
                    TimeoutError):
                # the replacement's cache sync lost to the error
                # schedule; the pod "crash-loops" and retries next tick
                pending_restarts.append((now, slot))
        for slot, replica in enumerate(replicas):
            if replica is None:
                continue
            before = replica.elector.owned_shards()
            replica.elector.tick()
            if not replica.elector.owned_shards():
                continue
            if before != replica.elector.owned_shards():
                monitor.trace.append(
                    f"[t={now:g}] {replica.identity} owns "
                    f"{sorted(replica.elector.owned_shards())}")
            injector.arm_due_crashes(now)
            replica.nudger.pop_due(now)
            replica.nudger.consume_pending()
            try:
                # delta-wired read path: apply the watch backlog (and
                # any rewatch/relist repair after a stream drop) before
                # this tick's snapshots
                replica.pump()
                replica.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                              remediation_policy)
                replica.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                          upgrade_policy)
                reconciles += 1
            except OperatorCrash:
                for shard in sorted(replica.elector.owned_shards()):
                    monitor.note_shard_orphaned(shard, now)
                replica.stop()
                try:
                    replicas[slot] = replace(
                        slot, "operator crash mid-reconcile")
                except (ApiServerError, ConflictError, NotFoundError,
                        TimeoutError):
                    replicas[slot] = None
                    pending_restarts.append((now, slot))
            except ShardFencedError as exc:
                # deposed mid-pass: the fence rejected the write and
                # the pass aborted — the replica re-derives its
                # partition from the Leases on its next tick
                fencings += 1
                monitor.trace.append(
                    f"[t={now:g}] {replica.identity} fenced: {exc}")
            except BuildStateError:
                pass
            except (ApiServerError, ConflictError, NotFoundError):
                pass
            if injector.fuse.pending:
                for shard in sorted(replica.elector.owned_shards()):
                    monitor.note_shard_orphaned(shard, now)
                replica.stop()
                try:
                    replicas[slot] = replace(
                        slot, "operator crash (surfaced late)")
                except (ApiServerError, ConflictError, NotFoundError,
                        TimeoutError):
                    replicas[slot] = None
                    pending_restarts.append((now, slot))
        # takeover detection: an orphaned shard is resumed once its
        # Lease is held by a LIVE replica again
        live_idents = {r.identity for r in replicas if r is not None}
        for shard in monitor.orphaned_shards():
            try:
                lease = cluster.get_lease(
                    config.lease_namespace,
                    f"{config.shard_lease_prefix}-shard-{shard:02d}")
            except NotFoundError:
                continue
            if lease.holder_identity in live_idents:
                monitor.note_shard_resumed(shard)
        monitor.drain()
        try:
            restore_workload_pods(cluster, fleet)
        except (ApiServerError, TimeoutError):
            pass
        monitor.drain()
        if (now > schedule.last_fault_time
                and not injector.fuse.armed
                and not injector.fuse.pending
                and not pending_restarts
                and converged()):
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        if not live_idents:
            # an all-replicas-dead window: nothing exists to adopt
            # anything, so this tick's span is excluded from the
            # takeover clocks (the invariant bounds the system, not
            # the schedule's double-kill windows)
            monitor.suspend_orphan_clock(config.reconcile_interval)
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    for replica in replicas:
        if replica is not None:
            replica.stop()
    if is_converged:
        monitor.final_check()
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"sharded fleet did not converge within "
                   f"{config.max_steps} steps ({clock.now():g}s "
                   f"virtual) after the last fault healed at "
                   f"{schedule.last_fault_time:g}s"))

    # harness sanity: the episode must have exercised what it gates
    if injector.replicas_killed < 1:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no replica kill fired"))
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))
    if injector.replicas_killed >= 1 \
            and not monitor.shard_takeover_seconds:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="monitor",
            detail="a replica was killed but no orphaned-shard "
                   "takeover was observed — the gate proved nothing "
                   "about ownership handover"))
    if monitor.shard_writes_audited == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="monitor",
            detail="zero durable writes were audited against the "
                   "shard leases"))
    if monitor.shard_takeover_seconds:
        monitor.trace.append(
            f"[t={clock.now():g}] orphaned-shard takeover times (s): "
            f"{sorted(round(s, 1) for s in monitor.shard_takeover_seconds)}"
            f" (grace {config.takeover_grace:g}s)")
    if fencings:
        monitor.trace.append(
            f"[t={clock.now():g}] {fencings} mid-pass fencing "
            f"rejection(s) (deposed replicas' writes refused)")

    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=injector.replicas_killed + injector.leader_losses,
        operator_incarnations=sum(generations),
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed)
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


@dataclass
class WindowChaosConfig(ChaosConfig):
    """Knobs of one maintenance-window soak episode.

    The fleet is deliberately heterogeneous — seeded lognormal delay
    spread plus named straggler hosts whose runtime pods take
    ``straggler_factor`` x the ready delay — so "finish by the close"
    genuinely cannot hold for every node and the deferral path has
    teeth. The episode is TWO rollouts: a learning rollout with no
    window (one full fleet pass — the model's cold-start budget, same
    framing as the planner bench), then a second rollout whose
    maintenance window closes ``window_seconds`` after its first pass;
    the stragglers' learned durations cross the close, so they must be
    deferred untouched while everything else finishes inside it.
    """

    n_slices: int = 4
    hosts_per_slice: int = 2
    straggler_nodes: tuple = ("s0-h0", "s2-h1")
    straggler_factor: float = 40.0
    hetero_sigma: float = 0.3
    #: Window length of rollout #2 (close = bump instant + this).
    window_seconds: float = 300.0
    window_margin_seconds: int = 60
    #: Ticks the fleet must hold a quiescent post-close state before
    #: the final audit.
    horizon: float = 700.0
    max_steps: int = 600


def run_window_soak(seed: int,
                    config: Optional[WindowChaosConfig] = None,
                    ) -> ChaosReport:
    """One seeded maintenance-window chaos episode; deterministic in
    ``seed``. Green means: under operator crashes and control-plane
    faults, every admission's predicted completion stayed inside the
    window, at least one straggler was deferred (and left untouched in
    upgrade-required), everything admitted finished before the episode
    end, and no node was stranded mid-upgrade at the close."""
    config = config or WindowChaosConfig()
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay,
        straggler_nodes=config.straggler_nodes,
        straggler_factor=config.straggler_factor,
        hetero_sigma=config.hetero_sigma)
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    node_names = [n.metadata.name for n in cluster.list_nodes()]

    schedule = FaultSchedule.generate_window(
        seed, node_names, horizon=config.horizon)
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name)
    injector.install()

    learning_policy = config.upgrade_policy()
    remediation_policy = config.remediation_policy()
    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        max_unavailable=learning_policy.max_unavailable,
        remediation_max_unavailable=remediation_policy.max_unavailable,
        max_parallel_upgrades=config.max_parallel_upgrades)

    incarnations = 1
    handovers = 0
    reconciles = 0

    def build_op(identity: str) -> _OperatorIncarnation:
        op = _OperatorIncarnation(cluster, clock, keys, rem_keys,
                                  config, injector, identity=identity,
                                  monitor=monitor)
        # the planner's admit/defer decision log must survive the
        # incarnation that made it: it lives on the monitor
        op.upgrade.window_audit = monitor.window_decision
        return op

    op = build_op("operator-1")

    def next_incarnation(reason: str) -> _OperatorIncarnation:
        nonlocal incarnations
        incarnations += 1
        injector.fuse.reset()
        monitor.trace.append(
            f"[t={clock.now():g}] operator restart #{incarnations} "
            f"({reason}) — rebuilding managers from cluster state alone")
        return build_op(f"operator-{incarnations}")

    def fleet_state() -> "tuple[int, int, int]":
        """(done, in_progress, pending) over the upgrade labels."""
        done = in_progress = pending = 0
        in_progress_labels = frozenset(str(s) for s in IN_PROGRESS_STATES)
        for node in cluster.list_nodes():
            label = node.metadata.labels.get(keys.state_label, "")
            if label == str(UpgradeState.DONE):
                done += 1
            elif label in in_progress_labels:
                in_progress += 1
            else:
                pending += 1
        return done, in_progress, pending

    def rollout_converged(revision: str) -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = [p for p in cluster.list_pods(namespace=NS)
                    if p.controller_owner() is not None]
        except (ApiServerError, TimeoutError):
            return False
        if any(n.metadata.labels.get(keys.state_label)
               != str(UpgradeState.DONE) or n.is_unschedulable()
               for n in nodes):
            return False
        return len(pods) == len(node_names) and all(
            p.metadata.labels.get(POD_CONTROLLER_REVISION_HASH_LABEL)
            == revision and p.is_ready() for p in pods)

    windowed_policy: Optional[UpgradePolicySpec] = None
    close: Optional[float] = None
    steps = 0
    quiesce_ticks = 0
    is_converged = False
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        was_leading = op.elector.is_leader
        op.elector.try_acquire_or_renew()
        if was_leading and not op.elector.is_leader:
            handovers += 1
            op = next_incarnation("leader election lost")
            op.elector.try_acquire_or_renew()
        if op.elector.is_leader:
            injector.arm_due_crashes(now)
            op.nudger.pop_due(now)
            op.nudger.consume_pending()
            policy = (windowed_policy if windowed_policy is not None
                      else learning_policy)
            try:
                op.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                         remediation_policy)
                op.upgrade.reconcile(NS, dict(RUNTIME_LABELS), policy)
                reconciles += 1
            except OperatorCrash:
                op = next_incarnation("operator crash mid-reconcile")
            except BuildStateError:
                pass
            except (ApiServerError, ConflictError, NotFoundError):
                pass
            if injector.fuse.pending:
                op = next_incarnation("operator crash (surfaced late)")
        monitor.drain()
        if windowed_policy is None:
            # An ARMED-but-unfired crash does NOT gate the bump: the
            # quiet tail of the learning rollout may carry too few
            # writes to detonate it, and the windowed rollout's write
            # burst is exactly where it should land.
            if not injector.fuse.pending and rollout_converged("new"):
                # learning rollout done: open the windowed rollout. The
                # close is measured from the bump instant, so it is
                # deterministic relative to the episode's own pacing.
                close = clock.now() + config.window_seconds
                windowed_policy = config.upgrade_policy()
                windowed_policy.maintenance_window = \
                    MaintenanceWindowSpec(
                        enable=True, close_epoch_seconds=close,
                        margin_seconds=config.window_margin_seconds)
                monitor.window = WindowExpectation(close_seconds=close)
                cluster.bump_daemon_set_revision(NS, "libtpu",
                                                 FINAL_REVISION)
                monitor.trace.append(
                    f"[t={clock.now():g}] windowed rollout opened: "
                    f"close t={close:g}, margin "
                    f"{config.window_margin_seconds}s")
        elif clock.now() > close and not injector.fuse.pending:
            try:
                _, in_progress, _ = fleet_state()
            except (ApiServerError, TimeoutError):
                in_progress = -1
            if in_progress == 0:
                quiesce_ticks += 1
                if quiesce_ticks >= 3:
                    is_converged = True
                    break
            else:
                quiesce_ticks = 0
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    if is_converged:
        monitor.final_check()
        done, in_progress, pending = fleet_state()
        # Teeth: the episode must have exercised BOTH window outcomes.
        if monitor.window_deferrals == 0 or pending == 0:
            monitor.violations.append(InvariantViolation(
                invariant="harness", at=clock.now(), subject="window",
                detail=f"no node was deferred by the window "
                       f"({monitor.window_deferrals} deferral "
                       f"decisions, {pending} pending at end) — the "
                       f"close never bit"))
        if monitor.window_admissions == 0 or done == 0:
            monitor.violations.append(InvariantViolation(
                invariant="harness", at=clock.now(), subject="window",
                detail=f"windowed rollout made no clean progress "
                       f"({done} done, {pending} pending, "
                       f"{in_progress} in progress)"))
        # Deferred nodes must be untouched: still schedulable, parked
        # in upgrade-required (never cordoned, never phase-stamped).
        for node in cluster.list_nodes():
            label = node.metadata.labels.get(keys.state_label, "")
            if label != str(UpgradeState.UPGRADE_REQUIRED):
                continue
            if node.is_unschedulable() \
                    or keys.phase_start_annotation \
                    in node.metadata.annotations:
                monitor.violations.append(InvariantViolation(
                    invariant="window-stranded", at=clock.now(),
                    subject=node.metadata.name,
                    detail="deferred node carries upgrade residue "
                           "(cordon or phase stamp) — it was started "
                           "after all"))
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"episode did not reach a quiescent post-close "
                   f"state within {config.max_steps} steps "
                   f"({clock.now():g}s virtual)"))
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))

    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=handovers,
        operator_incarnations=incarnations,
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed)
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


@dataclass
class BudgetChaosConfig(ChaosConfig):
    """Knobs of one traffic-aware-budget (diurnal replay) episode.

    The fleet SERVES throughout: one decode endpoint per node replaying
    a seeded diurnal QPS curve (chaos/serving.DiurnalTrace) while the
    whole fleet rolls to a new revision. The static policy is the 25%
    count a non-traffic-aware operator would ship; the capacity
    controller may raise the effective budget to ``max_effective``
    nodes in troughs and must shrink/pause/ABORT at peaks, spikes and
    node kills — with zero operator-caused dropped generations and
    zero capacity-SLO shortfall ticks.
    """

    #: 64 slices x 4 hosts = the 256-node acceptance fleet.
    n_slices: int = 64
    hosts_per_slice: int = 4
    #: Serving pods restart fast (decode images are warm); the drain
    #: phase — waiting out in-flight generations — dominates.
    pod_recreate_delay: float = 5.0
    pod_ready_delay: float = 10.0
    horizon: float = 700.0
    max_steps: int = 400
    #: Static policy budget (the non-traffic-aware equivalent).
    max_unavailable: IntOrString = "25%"
    #: Trough ceiling for the effective budget, as a fleet fraction —
    #: deliberately ABOVE the static 25% (the modulation proof needs
    #: the controller observed on both sides of the static line).
    max_effective_fraction: float = 0.4
    slo_headroom_fraction: float = 0.5
    peak_pause_utilization: float = 0.7
    per_node_capacity: int = 8
    #: Diurnal curve: utilization oscillates trough..peak over the
    #: period; spikes multiply it inside their windows.
    diurnal_period: float = 400.0
    trough_util: float = 0.12
    peak_util: float = 0.45
    generation_seconds: tuple = (15.0, 45.0)

    @property
    def total_nodes(self) -> int:
        return self.n_slices * self.hosts_per_slice

    def upgrade_policy(self) -> UpgradePolicySpec:
        return UpgradePolicySpec(
            auto_upgrade=True,
            max_parallel_upgrades=0,
            max_unavailable=self.max_unavailable,
            topology_mode="flat",
            drain=DrainSpec(enable=True, force=True,
                            timeout_seconds=300),
            predictor=PredictorSpec(enable=True),
            capacity=CapacityBudgetSpec(
                enable=True,
                slo_headroom_fraction=self.slo_headroom_fraction,
                max_effective_budget=int(
                    self.total_nodes * self.max_effective_fraction),
                peak_pause_utilization=self.peak_pause_utilization,
                per_node_capacity=self.per_node_capacity))


def budget_static_equivalent(config: BudgetChaosConfig,
                             trace: DiurnalTrace) -> int:
    """The peak-safe STATIC budget for this episode's trace: the node
    count an operator could leave unavailable at the WORST observed
    demand while keeping the SLO headroom — what a non-traffic-aware
    config would have to ship (and hold through every trough)."""
    import math

    peak = trace.peak_utilization(config.horizon)
    required = math.ceil(peak * (1.0 + config.slo_headroom_fraction)
                         * config.total_nodes)
    return max(0, config.total_nodes - required)


def run_budget_soak(seed: int,
                    config: Optional[BudgetChaosConfig] = None,
                    ) -> ChaosReport:
    """The traffic-aware disruption-budget gate: a serving fleet is
    upgraded end-to-end under a replayed diurnal load with traffic
    spikes, transient node kills and operator crash-restarts.

    What the episode proves, via the monitor's invariants plus the
    convergence check:

    - **capacity-slo**: at no tick did the offered load exceed what the
      admitting endpoints could place — the effective budget always
      left enough live capacity, through every drain wave, spike and
      kill (and zero generations were dropped by the operator: every
      eviction went through a quiesced serving gate);
    - **capacity-modulation**: the effective budget was observed both
      ABOVE the peak-safe static equivalent (troughs drained harder
      than any safe static count could) and BELOW it (peaks paused);
    - **abort arc**: at least one mid-flight abort fired (spike/kill
      collapsing the budget below current unavailability), and every
      observed ``abort-required -> upgrade-required`` commit was
      residue-free at the event instant (``abort-residue``);
    - plus the standing legal-transition / max-unavailable (armed at
      the effective ceiling) / cordon-pairing invariants, and full
      convergence: every node upgrade-done on the new revision with
      every endpoint admitting.

    Deterministic in ``seed``.
    """
    config = config or BudgetChaosConfig()
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay)
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    node_names = [n.metadata.name for n in cluster.list_nodes()]

    schedule = FaultSchedule.generate_budget(
        seed, node_names, horizon=config.horizon)
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name)
    injector.install()
    # rollout #2 mid-horizon, exactly like the main soak: guarantees
    # write traffic after every armed crash (an armed-but-unfired
    # crash would block convergence forever), and lands the second
    # rollout's drain waves on the trace's later spikes
    cluster.schedule_at(
        config.horizon / 2.0,
        lambda: cluster.bump_daemon_set_revision(NS, "libtpu",
                                                 FINAL_REVISION))
    # traffic spikes are harness-side faults (the injector has no
    # traffic to inflate): fold them into the diurnal trace
    spikes = tuple(SpikeWindow(at=e.at, until=e.until,
                               factor=e.param / 10.0,
                               ramp_seconds=60.0)
                   for e in schedule.by_kind(FAULT_TRAFFIC_SPIKE))
    trace = DiurnalTrace(seed=seed,
                         period_seconds=config.diurnal_period,
                         trough_util=config.trough_util,
                         peak_util=config.peak_util,
                         spikes=spikes)
    serving = ServingFleetSim(
        cluster, node_names, trace,
        per_node_capacity=config.per_node_capacity,
        generation_seconds=config.generation_seconds, seed=seed)

    upgrade_policy = config.upgrade_policy()
    remediation_policy = config.remediation_policy()
    # disabled for the episode (the bad-revision gate's rationale): a
    # transiently dead decode host must be attributed to the capacity
    # controller's reaction, not the remediation ladder — their
    # interplay is the main soak's job
    remediation_policy.enable = False
    # the modulation reference: the STATIC policy budget scaled against
    # the fleet — the count a non-traffic-aware config ships. The
    # effective budget must be observed above it (troughs) AND below
    # it (peaks/spikes); the trace-derived peak-safe bound is reported
    # alongside for context (it reaches 0 on big-spike seeds, where a
    # static config simply could not serve the episode at all).
    from tpu_operator_libs.api.upgrade_policy import (
        scaled_value_from_int_or_percent,
    )

    static_eq = scaled_value_from_int_or_percent(
        upgrade_policy.max_unavailable, config.total_nodes,
        round_up=True)
    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        # the over-disruption bound is the CEILING the controller may
        # reach in troughs, not the (lower) static policy count
        max_unavailable=upgrade_policy.capacity.max_effective_budget,
        remediation_max_unavailable=None,
        max_parallel_upgrades=config.max_parallel_upgrades,
        capacity=CapacityExpectation(static_equivalent=static_eq))
    capacity_log = CapacityLog()

    incarnations = 1
    handovers = 0
    reconciles = 0
    op = _OperatorIncarnation(cluster, clock, keys, rem_keys, config,
                              injector, identity="operator-1",
                              serving=serving, monitor=monitor)

    def next_incarnation(reason: str) -> _OperatorIncarnation:
        nonlocal incarnations
        incarnations += 1
        injector.fuse.reset()
        monitor.trace.append(
            f"[t={clock.now():g}] operator restart #{incarnations} "
            f"({reason}) — rebuilding managers from cluster state alone")
        return _OperatorIncarnation(
            cluster, clock, keys, rem_keys, config, injector,
            identity=f"operator-{incarnations}", serving=serving,
            monitor=monitor)

    def converged() -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = cluster.list_pods(namespace=NS)
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != len(node_names):
            return False
        for node in nodes:
            labels = node.metadata.labels
            if labels.get(keys.state_label) != str(UpgradeState.DONE):
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
        runtime = [p for p in pods if p.controller_owner() is not None]
        if len(runtime) != len(node_names):
            return False
        if not all(
                p.metadata.labels.get(POD_CONTROLLER_REVISION_HASH_LABEL)
                == FINAL_REVISION and p.is_ready() for p in runtime):
            return False
        # the serving fleet must be whole again: every node's endpoint
        # live and admitting
        return (len(serving.endpoints) == len(node_names)
                and not any(ep.draining
                            for ep in serving.endpoints.values()))

    steps = 0
    is_converged = False
    quiesce_ticks = 0
    # prime the replay BEFORE the first reconcile: the controller's
    # first evaluation must see live traffic, not the empty pre-start
    # fleet (an idle first glance would over-admit at a peak start)
    serving.tick(clock.now())
    monitor.drain()
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        was_leading = op.elector.is_leader
        op.elector.try_acquire_or_renew()
        if was_leading and not op.elector.is_leader:
            handovers += 1
            op = next_incarnation("leader election lost")
            op.elector.try_acquire_or_renew()
        if op.elector.is_leader:
            injector.arm_due_crashes(now)
            op.nudger.pop_due(now)
            op.nudger.consume_pending()
            try:
                op.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                         remediation_policy)
                op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                     upgrade_policy)
                reconciles += 1
            except OperatorCrash:
                op = next_incarnation("operator crash mid-reconcile")
            except BuildStateError:
                pass
            except (ApiServerError, ConflictError, NotFoundError):
                pass
            if injector.fuse.pending:
                op = next_incarnation("operator crash (surfaced late)")
        monitor.drain()
        # the serving replay: finish due generations, reconcile the
        # endpoints with pod/node reality, admit toward the trace
        load = serving.tick(now)
        controller = op.upgrade.capacity_controller
        status = (controller.last_status
                  if controller is not None else None)
        monitor.capacity_sample(load, status)
        capacity_log.record(load, status)
        monitor.drain()
        if (now > schedule.last_fault_time
                and not injector.fuse.armed
                and not injector.fuse.pending
                and converged()):
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    if is_converged:
        monitor.final_check()
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"serving fleet did not converge within "
                   f"{config.max_steps} steps ({clock.now():g}s "
                   f"virtual) after the last fault healed at "
                   f"{schedule.last_fault_time:g}s"))

    # the gate's unit of loss: zero generations dropped by the OPERATOR
    # (fault-killed hosts' losses are the schedule's, accounted apart)
    if serving.operator_dropped:
        monitor.violations.append(InvariantViolation(
            invariant="capacity-drop", at=clock.now(), subject="fleet",
            detail=f"{serving.operator_dropped} generation(s) dropped "
                   f"by upgrade evictions — the serving gate was "
                   f"bypassed or mis-sequenced"))
    # harness sanity: the episode must have exercised what it gates
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))
    if monitor.aborts_observed == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="monitor",
            detail="no mid-flight abort observed — the spikes/kills "
                   "never collapsed the budget below current "
                   "unavailability, so the abort arc proved nothing"))
    monitor.trace.append(
        f"[t={clock.now():g}] capacity: effective budget range "
        f"[{monitor.capacity_effective_min}, "
        f"{monitor.capacity_effective_max}] vs static policy budget "
        f"{static_eq} (trace peak-safe bound "
        f"{budget_static_equivalent(config, trace)}); "
        f"{monitor.aborts_observed} abort(s); serving "
        f"{serving.summary()}")

    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=handovers,
        operator_incarnations=incarnations,
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed)
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


@dataclass
class PreflightChaosConfig(BudgetChaosConfig):
    """Knobs of one rollout-preflight (read-only what-if) episode: the
    budget gate's 256-node serving fleet and compound-fault storm, with
    the preflight forecaster LIVE on every reconcile pass (advisory
    mode, so rejects never block the convergence the episode must still
    reach). The gate's teeth:

    - **preflight-readonly**: zero write attempts ever reach the frozen
      forecast clone and zero live-cluster mutations are attributable
      to a forecast — sampled every tick from the forecaster's lifetime
      evidence counters, across every operator incarnation (the
      forecast path shares the crash fuse, so detonations land INSIDE
      the forecast seam and must leave no residue);
    - **preflight-calibration**: a completed rollout's realized
      makespan lands within ``calibration_slack`` of the forecast made
      when its pending wave first appeared — the storm-grade sanity
      bound (the fault-free 15% bound is ``tools/preflight_bench.py``'s
      job);
    - **preflight-required-gate**: after convergence, a THIRD revision
      is offered under a ``required``-mode policy whose makespan
      threshold cannot be met — and zero nodes may enter any in-flight
      state while the audited reject stands.
    """

    #: Forecast confidence quantile for the error-histogram bounds.
    preflight_confidence: float = 0.9
    #: Storm-grade calibration bound: realized/forecast makespan ratio
    #: must land in [1/slack, slack] for the LAST completed rollout
    #: (the one forecast by the most-trained predictor). Deliberately
    #: loose — node kills, crash-restarts and peak pauses stretch the
    #: realized tail in ways the analytic forecast does not model.
    calibration_slack: float = 5.0
    #: Ticks of the post-convergence required-mode hold probe (0
    #: disables the probe).
    required_probe_steps: int = 12
    #: The unmeetable threshold the probe's policy ships: any real
    #: fleet forecast exceeds one second, so required mode MUST park.
    required_makespan_threshold: float = 1.0

    def upgrade_policy(self) -> UpgradePolicySpec:
        policy = super().upgrade_policy()
        policy.preflight = PreflightSpec(
            mode="advisory", confidence=self.preflight_confidence)
        return policy


#: The revision the required-mode hold probe offers after convergence —
#: never admitted (that is the point), so converged() never sees it.
HELD_REVISION = "new3hold"


def run_preflight_soak(seed: int,
                       config: Optional[PreflightChaosConfig] = None,
                       ) -> ChaosReport:
    """The rollout-preflight gate: the budget episode's serving fleet
    rolls end-to-end under the compound-fault storm with the what-if
    forecaster evaluated on every pass, proving the read-only
    guarantee, storm-grade forecast calibration, and the required-mode
    admission hold. Deterministic in ``seed``.
    """
    config = config or PreflightChaosConfig()
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay)
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    node_names = [n.metadata.name for n in cluster.list_nodes()]

    schedule = FaultSchedule.generate_budget(
        seed, node_names, horizon=config.horizon)
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name)
    injector.install()
    cluster.schedule_at(
        config.horizon / 2.0,
        lambda: cluster.bump_daemon_set_revision(NS, "libtpu",
                                                 FINAL_REVISION))
    spikes = tuple(SpikeWindow(at=e.at, until=e.until,
                               factor=e.param / 10.0,
                               ramp_seconds=60.0)
                   for e in schedule.by_kind(FAULT_TRAFFIC_SPIKE))
    trace = DiurnalTrace(seed=seed,
                         period_seconds=config.diurnal_period,
                         trough_util=config.trough_util,
                         peak_util=config.peak_util,
                         spikes=spikes)
    serving = ServingFleetSim(
        cluster, node_names, trace,
        per_node_capacity=config.per_node_capacity,
        generation_seconds=config.generation_seconds, seed=seed)

    upgrade_policy = config.upgrade_policy()
    remediation_policy = config.remediation_policy()
    remediation_policy.enable = False

    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        max_unavailable=upgrade_policy.capacity.max_effective_budget,
        remediation_max_unavailable=None,
        max_parallel_upgrades=config.max_parallel_upgrades)

    # the forecast path runs under the SAME crash fuse as the durable
    # writes: each computed forecast consumes one fuse unit, so the
    # schedule's detonations land inside the forecast seam too — and
    # the read-only invariant must hold across those crashes
    preflight_crashes = 0

    def preflight_guard(tag: str) -> None:
        nonlocal preflight_crashes
        before = injector.fuse.fired_total
        try:
            injector.fuse.guard(lambda: None)
        except OperatorCrash:
            if injector.fuse.fired_total > before:
                preflight_crashes += 1
            raise

    # forecaster evidence counters are per-incarnation (the forecaster
    # dies with the process); the invariant needs episode-lifetime
    # totals, so dead incarnations' counters are banked here
    accum = {"forecasts": 0, "cacheHits": 0, "rejected": 0,
             "frozenWriteAttempts": 0, "liveMutations": 0}

    def harvest(op: "_OperatorIncarnation") -> None:
        forecaster = op.upgrade.preflight
        if forecaster is None:
            return
        accum["forecasts"] += forecaster.forecasts_total
        accum["cacheHits"] += forecaster.cache_hits_total
        accum["rejected"] += forecaster.rejected_total
        accum["frozenWriteAttempts"] += \
            forecaster.frozen_write_attempts_total
        accum["liveMutations"] += forecaster.live_mutations_total

    def wire(op: "_OperatorIncarnation") -> "_OperatorIncarnation":
        # the soak's trace is the same object the serving sim replays,
        # so the forecast sweeps the real traffic shape; the guard is
        # the crash-fuse seam
        op.upgrade.preflight_trace = trace
        op.upgrade.preflight_guard = preflight_guard
        return op

    def probe_readonly(op: "_OperatorIncarnation") -> None:
        forecaster = op.upgrade.preflight
        if forecaster is None:
            return
        monitor.preflight_sample({
            "forecasts": accum["forecasts"]
            + forecaster.forecasts_total,
            "frozenWriteAttempts": accum["frozenWriteAttempts"]
            + forecaster.frozen_write_attempts_total,
            "liveMutations": accum["liveMutations"]
            + forecaster.live_mutations_total})

    incarnations = 1
    handovers = 0
    reconciles = 0
    op = wire(_OperatorIncarnation(
        cluster, clock, keys, rem_keys, config, injector,
        identity="operator-1", serving=serving, monitor=monitor))

    def next_incarnation(reason: str) -> "_OperatorIncarnation":
        nonlocal incarnations
        incarnations += 1
        harvest(op)
        injector.fuse.reset()
        monitor.trace.append(
            f"[t={clock.now():g}] operator restart #{incarnations} "
            f"({reason}) — rebuilding managers from cluster state alone")
        return wire(_OperatorIncarnation(
            cluster, clock, keys, rem_keys, config, injector,
            identity=f"operator-{incarnations}", serving=serving,
            monitor=monitor))

    done_label = str(UpgradeState.DONE)
    in_flight_labels = {str(s) for s in IN_PROGRESS_STATES}

    def fleet_done() -> bool:
        try:
            nodes = cluster.list_nodes()
        except (ApiServerError, TimeoutError):
            return False
        return (len(nodes) == len(node_names)
                and all(n.metadata.labels.get(keys.state_label)
                        == done_label for n in nodes))

    def converged() -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = cluster.list_pods(namespace=NS)
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != len(node_names):
            return False
        for node in nodes:
            if node.metadata.labels.get(keys.state_label) != done_label:
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
        runtime = [p for p in pods if p.controller_owner() is not None]
        if len(runtime) != len(node_names):
            return False
        if not all(
                p.metadata.labels.get(POD_CONTROLLER_REVISION_HASH_LABEL)
                == FINAL_REVISION and p.is_ready() for p in runtime):
            return False
        return (len(serving.endpoints) == len(node_names)
                and not any(ep.draining
                            for ep in serving.endpoints.values()))

    # forecast-vs-realized calibration: the first forecast that sees a
    # rollout's pending wave (with a warm, non-zero makespan) is held
    # until the fleet is all-done again — realized = done - generatedAt
    calib_active: "Optional[dict]" = None
    calib_samples: "list[dict]" = []

    steps = 0
    is_converged = False
    quiesce_ticks = 0
    serving.tick(clock.now())
    monitor.drain()
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        was_leading = op.elector.is_leader
        op.elector.try_acquire_or_renew()
        if was_leading and not op.elector.is_leader:
            handovers += 1
            op = next_incarnation("leader election lost")
            op.elector.try_acquire_or_renew()
        if op.elector.is_leader:
            injector.arm_due_crashes(now)
            op.nudger.pop_due(now)
            op.nudger.consume_pending()
            try:
                op.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                         remediation_policy)
                op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                     upgrade_policy)
                reconciles += 1
            except OperatorCrash:
                op = next_incarnation("operator crash mid-reconcile")
            except BuildStateError:
                pass
            except (ApiServerError, ConflictError, NotFoundError):
                pass
            if injector.fuse.pending:
                op = next_incarnation("operator crash (surfaced late)")
        monitor.drain()
        serving.tick(now)
        probe_readonly(op)
        forecast = op.upgrade.last_preflight
        if (calib_active is None and forecast is not None
                and forecast.get("nodesPending", 0) > 0
                and forecast["makespan"]["expectedSeconds"] > 0):
            calib_active = {
                "generatedAtSeconds": forecast["generatedAtSeconds"],
                "nodesPending": forecast["nodesPending"],
                "expectedSeconds":
                    forecast["makespan"]["expectedSeconds"],
                "lowerSeconds": forecast["makespan"]["lowerSeconds"],
                "upperSeconds": forecast["makespan"]["upperSeconds"],
                "errorSamples": forecast["makespan"]["errorSamples"]}
        if calib_active is not None and fleet_done():
            realized = now - calib_active["generatedAtSeconds"]
            if realized > 0:
                calib_active["realizedSeconds"] = round(realized, 1)
                calib_active["ratio"] = round(
                    realized / calib_active["expectedSeconds"], 3)
                calib_samples.append(calib_active)
                monitor.trace.append(
                    f"[t={now:g}] preflight calibration: forecast "
                    f"{calib_active['expectedSeconds']}s "
                    f"[{calib_active['lowerSeconds']}, "
                    f"{calib_active['upperSeconds']}] for "
                    f"{calib_active['nodesPending']} node(s), realized "
                    f"{calib_active['realizedSeconds']}s "
                    f"(ratio {calib_active['ratio']})")
            calib_active = None
        monitor.drain()
        if (now > schedule.last_fault_time
                and not injector.fuse.armed
                and not injector.fuse.pending
                and converged()):
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    if is_converged:
        monitor.final_check()
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"serving fleet did not converge within "
                   f"{config.max_steps} steps ({clock.now():g}s "
                   f"virtual) after the last fault healed at "
                   f"{schedule.last_fault_time:g}s"))

    # -- required-mode hold probe: a THIRD revision under an unmeetable
    # threshold must admit ZERO nodes while the audited reject stands
    required_verdict = ""
    required_admitted = 0
    probe_ran = False
    if is_converged and config.required_probe_steps > 0:
        probe_ran = True
        required_policy = config.upgrade_policy()
        required_policy.preflight = PreflightSpec(
            mode="required",
            max_forecast_makespan_seconds=(
                config.required_makespan_threshold),
            confidence=config.preflight_confidence)
        cluster.bump_daemon_set_revision(NS, "libtpu", HELD_REVISION)
        for _ in range(config.required_probe_steps):
            now = clock.now()
            op.elector.try_acquire_or_renew()
            if op.elector.is_leader:
                try:
                    op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                         required_policy)
                    reconciles += 1
                except (OperatorCrash, BuildStateError, ApiServerError,
                        ConflictError, NotFoundError):
                    pass
            monitor.drain()
            serving.tick(now)
            probe_readonly(op)
            forecast = op.upgrade.last_preflight
            if forecast is not None:
                required_verdict = forecast.get("verdict", "")
            try:
                nodes = cluster.list_nodes()
            except (ApiServerError, TimeoutError):
                nodes = []
            required_admitted = max(required_admitted, sum(
                1 for n in nodes
                if n.metadata.labels.get(keys.state_label)
                in in_flight_labels))
            clock.advance(config.reconcile_interval)
            cluster.step()
            monitor.drain()
        if required_verdict != "reject":
            monitor.violations.append(InvariantViolation(
                invariant="preflight-required-gate", at=clock.now(),
                subject="forecaster",
                detail=f"required-mode policy with an unmeetable "
                       f"makespan threshold never rejected (last "
                       f"verdict {required_verdict!r})"))
        if required_admitted:
            monitor.violations.append(InvariantViolation(
                invariant="preflight-required-gate", at=clock.now(),
                subject="fleet",
                detail=f"{required_admitted} node(s) entered an "
                       f"in-flight state under a standing required-mode "
                       f"preflight reject — the hold admitted work"))

    harvest(op)

    # -- storm-grade calibration gate ---------------------------------
    if not calib_samples:
        monitor.violations.append(InvariantViolation(
            invariant="preflight-calibration", at=clock.now(),
            subject="forecaster",
            detail="no completed rollout produced a forecast-vs-"
                   "realized sample — the forecaster never saw a "
                   "pending wave with a warm makespan"))
    else:
        # the LAST sample is the one the most-trained predictor made
        last = calib_samples[-1]
        slack = config.calibration_slack
        if not (1.0 / slack <= last["ratio"] <= slack):
            monitor.violations.append(InvariantViolation(
                invariant="preflight-calibration", at=clock.now(),
                subject="forecaster",
                detail=f"realized makespan {last['realizedSeconds']}s "
                       f"is {last['ratio']}x the forecast "
                       f"{last['expectedSeconds']}s — outside the "
                       f"storm-grade [{1.0 / slack:g}, {slack:g}] "
                       f"band"))

    # -- harness sanity: the episode must have exercised what it gates
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))
    if accum["forecasts"] == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="forecaster",
            detail="no preflight forecast was ever computed — the "
                   "gate never exercised the read-only path"))
    if monitor.preflight_samples == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="monitor",
            detail="preflight_sample never ran — the readonly "
                   "invariant had no evidence feed"))

    monitor.trace.append(
        f"[t={clock.now():g}] preflight: {accum['forecasts']} "
        f"forecast(s) ({accum['cacheHits']} cache hit(s), "
        f"{accum['rejected']} reject(s)), "
        f"{accum['frozenWriteAttempts']} frozen write attempt(s), "
        f"{accum['liveMutations']} live mutation(s), "
        f"{preflight_crashes} crash(es) mid-forecast, "
        f"{len(calib_samples)} calibration sample(s); serving "
        f"{serving.summary()}")

    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=handovers,
        operator_incarnations=incarnations,
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed)
    report.stats = {
        "preflight": dict(accum),
        "preflightCrashes": preflight_crashes,
        "preflightSamples": monitor.preflight_samples,
        "calibration": list(calib_samples),
        "requiredProbe": {
            "ran": probe_ran,
            "verdict": required_verdict,
            "admitted": required_admitted,
        },
    }
    report.report_text = "\n".join(
        [schedule.describe(),
         f"preflight: forecasts={accum['forecasts']} "
         f"cache_hits={accum['cacheHits']} "
         f"frozen_write_attempts={accum['frozenWriteAttempts']} "
         f"live_mutations={accum['liveMutations']} "
         f"crashes_mid_forecast={preflight_crashes} "
         f"preflight_samples={monitor.preflight_samples} "
         f"required_probe=({required_verdict or 'n/a'}, "
         f"admitted={required_admitted})",
         monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


@dataclass
class HandoverChaosConfig(BudgetChaosConfig):
    """Knobs of one zero-drop handover (class-aware diurnal replay)
    episode: the PR 10 budget gate's 256-node serving fleet at TWICE
    the trace amplitude (trough 0.24 / peak 0.90 vs 0.12 / 0.45), with
    the fleet split into traffic classes — a handful of SOLE-REPLICA
    interactive models (the nodes the ranker must hold behind the
    prewarm arc), replicated interactive pairs, and batch groups. The
    gate's teeth: ZERO operator-attributed dropped generations for any
    class (exact, per session id), zero interactive-class SLO breaches
    attributable to drains, zero prewarm crash residue.
    """

    trough_util: float = 0.24
    peak_util: float = 0.9
    #: Traffic layout (chaos/serving.assign_traffic knobs).
    interactive_fraction: float = 0.25
    sole_models: int = 3
    interactive_replicas: int = 2
    batch_replicas: int = 8
    #: Per-class drain deadlines: past these, in-flight sessions hand
    #: over to a peer replica so the drain can quiesce.
    interactive_drain_deadline: float = 60.0
    batch_drain_deadline: float = 30.0
    #: Batch's relaxed SLO: the shortfall fraction it may absorb.
    batch_shortfall_fraction: float = 0.3
    #: Seconds a prewarmed replica warms before passing readiness.
    prewarm_ready_seconds: float = 20.0

    def traffic_classes(self) -> "dict[str, TrafficClassSpec]":
        return {
            "interactive": TrafficClassSpec(
                name="interactive", interactive=True, min_replicas=1,
                drain_deadline_seconds=self.interactive_drain_deadline,
                max_shortfall_fraction=0.0),
            "batch": TrafficClassSpec(
                name="batch", interactive=False, min_replicas=1,
                drain_deadline_seconds=self.batch_drain_deadline,
                max_shortfall_fraction=self.batch_shortfall_fraction),
        }

    def assignments(self,
                    node_names: "list[str]",
                    ) -> "dict[str, tuple[str, str]]":
        return assign_traffic(
            node_names,
            interactive_fraction=self.interactive_fraction,
            sole_models=self.sole_models,
            interactive_replicas=self.interactive_replicas,
            batch_replicas=self.batch_replicas)

    def upgrade_policy(self) -> UpgradePolicySpec:
        policy = super().upgrade_policy()
        policy.capacity.traffic_classes = list(
            self.traffic_classes().values())
        policy.capacity.prewarm = True
        return policy


def run_handover_soak(seed: int,
                      config: Optional[HandoverChaosConfig] = None,
                      ) -> ChaosReport:
    """The zero-drop handover gate: the class-aware serving fleet is
    upgraded end-to-end at 2x the budget gate's traffic under spikes,
    transient node kills and operator crash-restarts, with the
    DisruptionCostRanker + prewarm arc + router-side session handover
    live.

    What the episode proves, via the monitor's invariants plus the
    runner's own checks:

    - **zero-drop**: not one generation of ANY class was dropped by an
      operator eviction — checked per SESSION id (exact attribution),
      not by count;
    - **class-slo**: the interactive class's admission shortfall was
      zero at every tick (modulo pure overload/fault, which even an
      undrained fleet could not have served) and no interactive model
      was ever operator-drained dark — batch degraded only within its
      relaxed allowance;
    - **prewarm residue**: the converged fleet carries not a single
      prewarm reservation/ready stamp, across every operator crash —
      aborted prewarms resume or release from durable state alone;
    - plus the standing legal-transition / max-unavailable /
      cordon-pairing / decision-audit invariants and full convergence
      with every prewarmed replica gracefully retired.

    Deterministic in ``seed``.
    """
    config = config or HandoverChaosConfig()
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay)
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    node_names = [n.metadata.name for n in cluster.list_nodes()]

    schedule = FaultSchedule.generate_handover(
        seed, node_names, horizon=config.horizon)
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name)
    injector.install()
    # rollout #2 mid-horizon (the budget gate's rationale): guarantees
    # write traffic after every armed crash and a second pass through
    # the hold -> prewarm -> drain arc for every sole-replica model
    cluster.schedule_at(
        config.horizon / 2.0,
        lambda: cluster.bump_daemon_set_revision(NS, "libtpu",
                                                 FINAL_REVISION))
    spikes = tuple(SpikeWindow(at=e.at, until=e.until,
                               factor=e.param / 10.0,
                               ramp_seconds=60.0)
                   for e in schedule.by_kind(FAULT_TRAFFIC_SPIKE))
    trace = DiurnalTrace(seed=seed,
                         period_seconds=config.diurnal_period,
                         trough_util=config.trough_util,
                         peak_util=config.peak_util,
                         spikes=spikes)
    classes = config.traffic_classes()
    serving = ServingFleetSim(
        cluster, node_names, trace,
        per_node_capacity=config.per_node_capacity,
        generation_seconds=config.generation_seconds, seed=seed,
        classes=classes,
        assignments=config.assignments(node_names),
        prewarm_ready_seconds=config.prewarm_ready_seconds)

    upgrade_policy = config.upgrade_policy()
    remediation_policy = config.remediation_policy()
    remediation_policy.enable = False
    from tpu_operator_libs.api.upgrade_policy import (
        scaled_value_from_int_or_percent,
    )

    static_eq = scaled_value_from_int_or_percent(
        upgrade_policy.max_unavailable, config.total_nodes,
        round_up=True)
    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        max_unavailable=upgrade_policy.capacity.max_effective_budget,
        remediation_max_unavailable=None,
        max_parallel_upgrades=config.max_parallel_upgrades,
        capacity=CapacityExpectation(static_equivalent=static_eq,
                                     classes=classes, zero_drop=True))
    capacity_log = CapacityLog()

    incarnations = 1
    handovers = 0
    reconciles = 0
    op = _OperatorIncarnation(cluster, clock, keys, rem_keys, config,
                              injector, identity="operator-1",
                              serving=serving, monitor=monitor)

    def next_incarnation(reason: str) -> _OperatorIncarnation:
        nonlocal incarnations
        incarnations += 1
        injector.fuse.reset()
        monitor.trace.append(
            f"[t={clock.now():g}] operator restart #{incarnations} "
            f"({reason}) — rebuilding managers from cluster state alone")
        return _OperatorIncarnation(
            cluster, clock, keys, rem_keys, config, injector,
            identity=f"operator-{incarnations}", serving=serving,
            monitor=monitor)

    def converged() -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = cluster.list_pods(namespace=NS)
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != len(node_names):
            return False
        for node in nodes:
            labels = node.metadata.labels
            if labels.get(keys.state_label) != str(UpgradeState.DONE):
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
        runtime = [p for p in pods if p.controller_owner() is not None]
        if len(runtime) != len(node_names):
            return False
        if not all(
                p.metadata.labels.get(POD_CONTROLLER_REVISION_HASH_LABEL)
                == FINAL_REVISION and p.is_ready() for p in runtime):
            return False
        # the serving fleet must be whole again: every node's endpoint
        # live and admitting, every prewarmed replica gracefully
        # retired (no replacement may outlive its incumbent's return)
        return (len(serving.endpoints) == len(node_names)
                and not any(ep.draining
                            for ep in serving.endpoints.values())
                and not serving.prewarmed)

    steps = 0
    is_converged = False
    quiesce_ticks = 0
    serving.tick(clock.now())
    monitor.drain()
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        was_leading = op.elector.is_leader
        op.elector.try_acquire_or_renew()
        if was_leading and not op.elector.is_leader:
            handovers += 1
            op = next_incarnation("leader election lost")
            op.elector.try_acquire_or_renew()
        if op.elector.is_leader:
            injector.arm_due_crashes(now)
            op.nudger.pop_due(now)
            op.nudger.consume_pending()
            try:
                op.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                         remediation_policy)
                op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                     upgrade_policy)
                reconciles += 1
            except OperatorCrash:
                op = next_incarnation("operator crash mid-reconcile")
            except BuildStateError:
                pass
            except (ApiServerError, ConflictError, NotFoundError):
                pass
            if injector.fuse.pending:
                op = next_incarnation("operator crash (surfaced late)")
        monitor.drain()
        load = serving.tick(now)
        controller = op.upgrade.capacity_controller
        status = (controller.last_status
                  if controller is not None else None)
        monitor.capacity_sample(load, status)
        capacity_log.record(load, status, classes=classes)
        monitor.drain()
        if (now > schedule.last_fault_time
                and not injector.fuse.armed
                and not injector.fuse.pending
                and converged()):
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    if is_converged:
        monitor.final_check()
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"serving fleet did not converge within "
                   f"{config.max_steps} steps ({clock.now():g}s "
                   f"virtual) after the last fault healed at "
                   f"{schedule.last_fault_time:g}s"))

    # zero-drop, per SESSION: the sim's seed-pure session ids make the
    # attribution exact — one operator-dropped session is a violation,
    # named, not counted
    for record in serving.operator_drop_records():
        monitor.violations.append(InvariantViolation(
            invariant="zero-drop", at=record["at"],
            subject=record["session"],
            detail=f"session {record['session']} (model "
                   f"{record['model']}, class {record['class']}) was "
                   f"dropped by an upgrade eviction — the serving "
                   f"gate was bypassed or mis-sequenced"))
    # prewarm crash residue: the converged fleet must carry no
    # reservation/ready stamp on any node (aborted prewarms resume or
    # release from durable state alone)
    if is_converged:
        try:
            residue_nodes = cluster.list_nodes()
        except (ApiServerError, TimeoutError):
            residue_nodes = []
        for node in residue_nodes:
            for key in (keys.prewarm_reservation_annotation,
                        keys.prewarm_ready_annotation):
                if key in node.metadata.annotations:
                    monitor.violations.append(InvariantViolation(
                        invariant="prewarm-residue", at=clock.now(),
                        subject=node.metadata.name,
                        detail=f"converged fleet still carries "
                               f"{key}="
                               f"{node.metadata.annotations[key]!r} "
                               f"— an aborted prewarm left durable "
                               f"residue"))
    # harness sanity: the episode must have exercised what it gates
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))
    if serving.prewarms_started == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="serving",
            detail="no prewarm was ever started — the sole-replica "
                   "holds never drove the reserve->ready arc, so the "
                   "gate proved nothing about it"))
    monitor.trace.append(
        f"[t={clock.now():g}] handover: effective budget range "
        f"[{monitor.capacity_effective_min}, "
        f"{monitor.capacity_effective_max}] vs static {static_eq}; "
        f"{monitor.aborts_observed} abort(s); "
        f"{serving.handovers} session handover(s); prewarms "
        f"{serving.prewarms_started}/{serving.prewarms_ready}/"
        f"{serving.prewarms_retired} started/ready/retired; serving "
        f"{serving.summary()}")

    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=handovers,
        operator_incarnations=incarnations,
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed)
    report.report_text = "\n".join(
        [schedule.describe(), monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


# ---------------------------------------------------------------------------
# multi-artifact upgrade-DAG soak (ISSUE 15 — policy/dag.py)
# ---------------------------------------------------------------------------

#: Broken artifact build injected mid-horizon (single hash segment, the
#: FakeCluster revision-name rule). Distinct from the primary-runtime
#: BAD_REVISION_HASH: this one is contained by the DAG coordinator's
#: quarantine + suffix rollback, not the RolloutGuard.
BAD_ARTIFACT_HASH = "badart"


@dataclass
class DagChaosConfig(ChaosConfig):
    """Knobs of one DAG soak episode.

    The fleet runs FOUR DaemonSet-delivered artifacts in a diamond:
    libtpu (primary) -> {device-plugin, network-driver} -> os-image.
    Everything the scenario needs is DECLARATIVE — the policy document
    carries the DAG and the hook programs; the soak makes zero
    operator-code changes (the acceptance property of ISSUE 15).
    """

    #: Crash-looping nodes at an artifact's target revision that
    #: quarantine it. 2: a single crashloop-fault window (one node)
    #: can never condemn a good revision, while the injected bad
    #: artifact parks every node it reaches and crosses the threshold.
    failure_threshold: int = 2
    #: Delete one seeded node mid-horizon (scale-down "node kill"):
    #: its stamps and pods vanish mid-DAG and the fleet must converge
    #: over the survivors.
    kill_node: bool = True
    #: Extra headroom over the base soak: every node runs TWO shared
    #: cordon/drain cycles (initial rollout + the mid-horizon bumps)
    #: with the bad-artifact containment arc in between.
    max_steps: int = 2000

    #: artifact name -> DaemonSet/pod labels (the non-primary three).
    ARTIFACT_LABELS = {
        "device-plugin": {"app": "tpu-device-plugin"},
        "network-driver": {"app": "tpu-network-driver"},
        "os-image": {"app": "node-os-image"},
    }

    def dag_spec(self) -> "object":
        from tpu_operator_libs.api.policy_spec import (
            ArtifactDAGSpec,
            ArtifactSpec,
        )

        return ArtifactDAGSpec(
            enable=True,
            failure_threshold=self.failure_threshold,
            artifacts=[
                ArtifactSpec(name="libtpu",
                             runtime_labels=dict(RUNTIME_LABELS)),
                ArtifactSpec(
                    name="device-plugin",
                    runtime_labels=dict(
                        self.ARTIFACT_LABELS["device-plugin"]),
                    depends_on=["libtpu"]),
                ArtifactSpec(
                    name="network-driver",
                    runtime_labels=dict(
                        self.ARTIFACT_LABELS["network-driver"]),
                    depends_on=["libtpu"]),
                ArtifactSpec(
                    name="os-image",
                    runtime_labels=dict(
                        self.ARTIFACT_LABELS["os-image"]),
                    depends_on=["device-plugin", "network-driver"]),
            ])

    def policy_hooks_spec(self) -> "object":
        """Benign declarative programs on three hook points: the
        sandbox runs LIVE under the gate (eval counters are the
        policy-sandbox invariant's teeth) while steering nothing the
        invariants depend on."""
        from tpu_operator_libs.api.policy_spec import (
            HookProgramSpec,
            PolicyHooksSpec,
        )

        return PolicyHooksSpec(hooks=[
            HookProgramSpec(
                hook="planner.admission",
                program="fleet.unavailable <= fleet.budget "
                        "|| fleet.slots >= 0"),
            HookProgramSpec(
                hook="eviction.filter",
                program="size(pods) >= 0 && !has(node.labels, "
                        "\"chaos/never\")"),
            HookProgramSpec(
                hook="validation.verdict",
                program="node.name != \"\""),
        ])

    def upgrade_policy(self) -> UpgradePolicySpec:
        policy = super().upgrade_policy()
        policy.artifact_dag = self.dag_spec()
        policy.policy_hooks = self.policy_hooks_spec()
        return policy


def run_dag_soak(seed: int,
                 config: Optional[DagChaosConfig] = None) -> ChaosReport:
    """One seeded multi-artifact DAG episode; deterministic in ``seed``.

    The scenario (all of it expressed as policy + spec — the operator
    code is untouched by the config):

    1. Four artifact DaemonSets (diamond DAG) roll old -> "new" at t0;
       every node advances all four through ONE shared cordon/drain
       cycle in dependency order, stamping durable per-artifact
       revisions.
    2. Mid-horizon, libtpu and device-plugin bump to "new2", os-image
       to "new2" — and network-driver to a BROKEN build
       (:data:`BAD_ARTIFACT_HASH`) whose pods can never become Ready.
       The coordinator must quarantine it (durable DS annotation,
       crash-ordered before the rollback), roll network-driver back to
       "new", and contain the failure to the dependent suffix alone:
       os-image (un-started, depends on the condemned arc) rolls back
       to "new" while libtpu/device-plugin keep rolling to "new2".
    3. The standard compound-fault storm runs throughout (operator
       crashes inside the stamp seam included), plus one seeded node
       DELETION mid-horizon (kill_node).

    Always-on invariants: the base catalog + ``dag-order`` (no
    artifact advances before its dependencies' stamps; the suffix
    never runs "new2") and ``policy-sandbox`` (hook failures always
    audited; no pass ever wedges on a policy).
    """
    import random as _random

    config = config or DagChaosConfig()
    victim = None
    removals: "tuple" = ()
    all_names = [f"s{s}-h{h}" for s in range(config.n_slices)
                 for h in range(config.hosts_per_slice)]
    if config.kill_node:
        rng = _random.Random(f"dag-kill:{seed}")
        victim = rng.choice(all_names)
        removals = ((victim,
                     config.horizon * (0.25 + 0.35 * rng.random())),)
    fleet = FleetSpec(
        n_slices=config.n_slices,
        hosts_per_slice=config.hosts_per_slice,
        pod_recreate_delay=config.pod_recreate_delay,
        pod_ready_delay=config.pod_ready_delay,
        multislice_jobs=(
            ("chaos-job", tuple(range(config.n_slices))),),
        node_removals=removals)
    cluster, clock, keys = build_fleet(fleet)
    rem_keys = RemediationKeys()
    node_names = [n.metadata.name for n in cluster.list_nodes()]
    surviving = [n for n in node_names if n != victim]

    from tpu_operator_libs.simulate import seed_artifact_daemon_sets

    seed_artifact_daemon_sets(cluster, config.ARTIFACT_LABELS,
                              revision_hash="old")
    for name in config.ARTIFACT_LABELS:
        cluster.bump_daemon_set_revision(NS, name, "new")
    # the broken network-driver build: pods recreated from it
    # crash-loop forever — recovery is the coordinator's quarantine +
    # suffix rollback or nothing
    cluster.add_pod_ready_gate(
        lambda pod: pod.metadata.labels.get(
            POD_CONTROLLER_REVISION_HASH_LABEL) != BAD_ARTIFACT_HASH)

    def mid_horizon_bumps() -> None:
        cluster.bump_daemon_set_revision(NS, "libtpu", FINAL_REVISION)
        cluster.bump_daemon_set_revision(NS, "device-plugin",
                                         FINAL_REVISION)
        cluster.bump_daemon_set_revision(NS, "network-driver",
                                         BAD_ARTIFACT_HASH)
        cluster.bump_daemon_set_revision(NS, "os-image", FINAL_REVISION)

    cluster.schedule_at(config.horizon / 2.0, mid_horizon_bumps)

    # faults target only survivors: a flap/stale action firing against
    # the deleted victim would crash the SIM, not the system under test
    schedule = FaultSchedule.generate(
        seed, surviving, horizon=config.horizon,
        extra_kinds=config.extra_fault_kinds)
    injector = ChaosInjector(cluster, schedule,
                             lease_namespace=config.lease_namespace,
                             lease_name=config.lease_name)
    injector.install()

    upgrade_policy = config.upgrade_policy()
    remediation_policy = config.remediation_policy()
    dag_spec = upgrade_policy.artifact_dag
    monitor = InvariantMonitor(
        cluster=cluster, upgrade_keys=keys, remediation_keys=rem_keys,
        max_unavailable=upgrade_policy.max_unavailable,
        remediation_max_unavailable=remediation_policy.max_unavailable,
        max_parallel_upgrades=config.max_parallel_upgrades,
        dag=DagExpectation(
            deps={a.name: tuple(a.depends_on)
                  for a in dag_spec.artifacts},
            stamp_prefix=keys.artifact_stamp_prefix,
            apps={labels["app"]: name for name, labels in
                  {**config.ARTIFACT_LABELS,
                   "libtpu": dict(RUNTIME_LABELS)}.items()},
            runtime_namespace=NS,
            forbidden=(("os-image", FINAL_REVISION),)))

    incarnations = 1
    handovers = 0
    reconciles = 0
    policy_evals_total = 0

    def engine_stats(op: _OperatorIncarnation) -> "Optional[dict]":
        engine = op.upgrade.policy_engine
        if engine is None:
            return None
        return engine.registry.stats()

    op = _OperatorIncarnation(cluster, clock, keys, rem_keys, config,
                              injector, identity="operator-1",
                              monitor=monitor)

    def next_incarnation(reason: str) -> _OperatorIncarnation:
        nonlocal incarnations, policy_evals_total
        incarnations += 1
        stats = engine_stats(op)
        if stats is not None:
            # the dying incarnation's sandbox evidence (counters die
            # with the process; the teeth total lives in the harness)
            policy_evals_total += sum(stats["evalsTotal"].values())
        injector.fuse.reset()
        monitor.trace.append(
            f"[t={clock.now():g}] operator restart #{incarnations} "
            f"({reason}) — rebuilding managers from cluster state alone")
        return _OperatorIncarnation(
            cluster, clock, keys, rem_keys, config, injector,
            identity=f"operator-{incarnations}", monitor=monitor)

    #: artifact -> expected final revision (the containment picture).
    final_targets = {
        "libtpu": FINAL_REVISION,
        "device-plugin": FINAL_REVISION,
        "network-driver": "new",
        "os-image": "new",
    }

    def converged() -> bool:
        try:
            nodes = cluster.list_nodes()
            pods = cluster.list_pods(namespace=NS)
            nd = cluster.list_daemon_sets(NS, "app=tpu-network-driver")
        except (ApiServerError, TimeoutError):
            return False
        if len(nodes) != len(surviving):
            return False
        for node in nodes:
            labels = node.metadata.labels
            if labels.get(keys.state_label) != str(UpgradeState.DONE):
                return False
            if labels.get(rem_keys.state_label, ""):
                return False
            if keys.skip_label in labels:
                return False
            if node.is_unschedulable() or not node.is_ready():
                return False
            for artifact, target in final_targets.items():
                if node.metadata.annotations.get(
                        keys.artifact_stamp_prefix + artifact) != target:
                    return False
        # the quarantine record must be durable on the condemned DS
        if not nd or nd[0].metadata.annotations.get(
                keys.quarantined_revision_annotation) \
                != BAD_ARTIFACT_HASH:
            return False
        by_app: "dict[str, list]" = {}
        for pod in pods:
            if pod.controller_owner() is None:
                continue
            by_app.setdefault(
                pod.metadata.labels.get("app", ""), []).append(pod)
        app_target = {"libtpu": FINAL_REVISION,
                      "tpu-device-plugin": FINAL_REVISION,
                      "tpu-network-driver": "new",
                      "node-os-image": "new"}
        for app, target in app_target.items():
            group = by_app.get(app, [])
            if len(group) != len(surviving):
                return False
            if not all(
                    p.metadata.labels.get(
                        POD_CONTROLLER_REVISION_HASH_LABEL) == target
                    and p.is_ready() for p in group):
                return False
        return True

    steps = 0
    is_converged = False
    quiesce_ticks = 0
    while steps < config.max_steps:
        steps += 1
        now = clock.now()
        was_leading = op.elector.is_leader
        op.elector.try_acquire_or_renew()
        if was_leading and not op.elector.is_leader:
            handovers += 1
            op = next_incarnation("leader election lost")
            op.elector.try_acquire_or_renew()
        if op.elector.is_leader:
            injector.arm_due_crashes(now)
            op.nudger.pop_due(now)
            op.nudger.consume_pending()
            try:
                op.remediation.reconcile(NS, dict(RUNTIME_LABELS),
                                         remediation_policy)
                op.upgrade.reconcile(NS, dict(RUNTIME_LABELS),
                                     upgrade_policy)
                reconciles += 1
            except OperatorCrash:
                op = next_incarnation("operator crash mid-reconcile")
            except BuildStateError:
                pass  # incomplete snapshot; next tick retries
            except (ApiServerError, ConflictError, NotFoundError):
                pass  # pass aborted on a transient; next tick retries
            except Exception as exc:  # noqa: BLE001 — the sandbox
                # contract: with policy hooks active NOTHING else may
                # escape a reconcile; an escape IS the wedge the
                # policy-sandbox invariant forbids
                monitor.violations.append(InvariantViolation(
                    invariant="policy-sandbox", at=clock.now(),
                    subject="operator",
                    detail=f"reconcile raised through the policy "
                           f"sandbox: {type(exc).__name__}: {exc}"))
            if injector.fuse.pending:
                op = next_incarnation("operator crash (surfaced late)")
            monitor.policy_sample(engine_stats(op))
        monitor.drain()
        if steps % 5 == 0 and op.upgrade.last_state is not None:
            for parked in monitor.parked_nodes():
                monitor.audit_explain(parked,
                                      op.upgrade.explain(parked))
        try:
            restore_workload_pods(cluster, fleet)
        except (ApiServerError, TimeoutError):
            pass  # injected fault; the JobSet controller retries too
        monitor.drain()
        if (now > schedule.last_fault_time
                and now > config.horizon / 2.0
                and not injector.fuse.armed
                and not injector.fuse.pending
                and converged()):
            quiesce_ticks += 1
            if quiesce_ticks >= 3:
                is_converged = True
                break
        else:
            quiesce_ticks = 0
        clock.advance(config.reconcile_interval)
        cluster.step()
        monitor.drain()

    stats = engine_stats(op)
    if stats is not None:
        policy_evals_total += sum(stats["evalsTotal"].values())

    if is_converged:
        monitor.final_check()
    else:
        monitor.violations.append(InvariantViolation(
            invariant="liveness", at=clock.now(), subject="fleet",
            detail=f"fleet did not converge within {config.max_steps} "
                   f"steps ({clock.now():g}s virtual) after the last "
                   f"fault healed at {schedule.last_fault_time:g}s"))

    # harness sanity: the episode must have exercised what it claims
    if injector.crashes_fired == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="injector",
            detail="no operator crash fired — the schedule's crash "
                   "events never detonated"))
    if monitor.dag_stamps_seen == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="dag",
            detail="no artifact revision stamp was ever observed — "
                   "the DAG coordinator never advanced anything"))
    if policy_evals_total == 0:
        monitor.violations.append(InvariantViolation(
            invariant="harness", at=clock.now(), subject="policy",
            detail="no policy hook evaluation ran — the sandbox was "
                   "never exercised"))

    report = ChaosReport(
        seed=seed,
        converged=is_converged,
        violations=list(monitor.violations),
        fault_kinds=tuple(sorted(schedule.kinds)),
        crashes_fired=injector.crashes_fired,
        leader_handovers=handovers,
        operator_incarnations=incarnations,
        watch_gaps=monitor.watch_gaps,
        total_seconds=clock.now(),
        steps=steps,
        reconciles=reconciles,
        trace=list(monitor.trace),
        decisions_recorded=monitor.decisions_recorded,
        explains_probed=monitor.explains_probed)
    report.report_text = "\n".join(
        [schedule.describe(),
         f"dag: victim={victim} stamps_seen={monitor.dag_stamps_seen} "
         f"advances_seen={monitor.dag_advances_seen} "
         f"policy_evals={policy_evals_total} "
         f"policy_samples={monitor.policy_samples}",
         monitor.report(seed=seed)])
    if not report.ok:
        logger.error("%s", report.report_text)
    return report


def run_many(seeds: "list[int]",
             config: Optional[ChaosConfig] = None) -> "list[ChaosReport]":
    """Convenience sweep used by ``make test-chaos`` and the soak test."""
    reports = [run_chaos_soak(seed, config) for seed in seeds]
    for report in reports:
        logger.info("%s", report.summary())
    return reports
