"""InvariantMonitor: watch-driven safety assertions for chaos runs.

Subscribes to the cluster's watch stream and checks, after every
mutation, that the two state machines held their contracts no matter
what the fault schedule did:

- **legal-transition**: a node's upgrade-state label only ever moves
  along ``consts.STATE_EDGES``; its remediation label only along
  ``consts.REMEDIATION_EDGES``.
- **max-unavailable**: at every admission instant (a node entering
  ``cordon-required`` in either machine), fleet unavailability plus the
  nodes committed-to-cordon stays within the policy budget. Nodes that
  were already unschedulable are exempt (the documented manual-cordon
  override); the check is only armed for the flat planner — the slice
  planner may deliberately overdraw by one slice (topology/planner.py
  point 4).
- **max-parallel**: at admission, upgrades in progress never exceed
  ``maxParallelUpgrades`` (when set).
- **workload-placement**: no workload pod is ever scheduled onto a
  cordoned node or one whose state says its runtime is being torn down
  (``consts.WORKLOAD_UNSAFE_STATES`` /
  ``REMEDIATION_WORKLOAD_UNSAFE_STATES``).
- **cordon-pairing** (checked at the end via :meth:`final_check`):
  every cordon the operators applied was eventually paired with an
  uncordon — no node is left quarantined once the fleet converged.

The monitor mirrors cluster state from events only; when its stream is
broken (the ``watch-break`` fault) or overflows (a BOOKMARK marker from
a bounded Watch), it resubscribes and relists — transitions hidden by
the gap are absorbed without assertion, exactly the blind spot a real
informer has, and the gap itself is recorded in the trace.

Every event lands in a bounded trace; a violation report carries the
seed and that trace, which is all that is needed to replay the run
(``FaultSchedule`` is pure in the seed).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpu_operator_libs.api.upgrade_policy import (
    IntOrString,
    scaled_value_from_int_or_percent,
)
from tpu_operator_libs.chaos.injector import consume_transient
from tpu_operator_libs.consts import (
    ABORTABLE_STATES,
    GKE_NODEPOOL_LABEL,
    IN_PROGRESS_STATES,
    LEGAL_EDGES,
    POD_CONTROLLER_REVISION_HASH_LABEL,
    REMEDIATION_LEGAL_EDGES,
    REMEDIATION_WORKLOAD_UNSAFE_STATES,
    WORKLOAD_UNSAFE_STATES,
    RemediationKeys,
    RemediationState,
    TopologyKeys,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.k8s.fake import FakeCluster
from tpu_operator_libs.k8s.watch import (
    ADDED,
    BOOKMARK,
    DELETED,
    KIND_NODE,
    KIND_POD,
)

logger = logging.getLogger(__name__)

_IN_PROGRESS = frozenset(str(s) for s in IN_PROGRESS_STATES)


@dataclass(frozen=True)
class RolloutExpectation:
    """Arms the rollout (canary halt + rollback) invariants.

    ``bad_revision`` is the revision hash the scenario condemned. The
    monitor then asserts, from watch events alone:

    - **rollout-halt**: after it has itself observed
      ``failure_threshold`` distinct nodes enter ``upgrade-failed``
      while carrying the bad revision, NO node is admitted into the
      upgrade flow (into ``cordon-required``, or newly into
      ``upgrade-required``) until a rollback signal appears — a
      bad-revision runtime pod being deleted, or a runtime pod of any
      other revision being created (either proves the DaemonSet was
      re-pinned). Event order makes "within one reconcile pass" exact:
      a pass that admits before its snapshot could contain the verdicts
      emits its admissions BEFORE the verdict labels, so any admission
      event AFTER the threshold verdict and BEFORE the rollback signal
      is a genuine halt breach.
    - **rollout-bad-pod**: no runtime pod carrying the bad revision is
      created later than ``bad_pod_grace_seconds`` after the halt
      evidence — recreations already in flight when the halt landed get
      the grace; anything later means a restart re-attempted the
      quarantined revision.
    """

    bad_revision: str
    failure_threshold: int = 1
    runtime_namespace: str = "tpu-system"
    bad_pod_grace_seconds: float = 30.0


@dataclass(frozen=True)
class ReconfigExpectation:
    """Arms the degraded-slice reconfiguration invariants.

    The monitor learns each slice's full shape (host count per nodepool)
    at its initial sync, then asserts from watch events alone:

    - **slice-placement**: a slice's member count never drops below its
      expected shape minus the hosts durably admitted as lost in the
      runtime DaemonSet's degraded-slices annotation — every multislice
      job's placement is full or DECLARED degraded, never silently
      short. (Join-before-release ordering in the reconfigurer makes a
      correct remap invisible to this check.)
    - **reconfig-joint-plan**: a node joining a slice it was not an
      original member of (a remapped spare) must carry a runtime pod on
      ``target_revision``, must join schedulable, and must never be
      cordoned again afterwards — the joint plan gave it its one
      cordon/drain cycle while still out of the slice, so any later
      cordon is a second disruption the remap was supposed to avoid.

    Condemned→remapped durations are accumulated in
    ``InvariantMonitor.remap_seconds`` (the report's MTTR-style
    evidence).
    """

    topology_keys: TopologyKeys
    target_revision: str
    runtime_namespace: str = "tpu-system"


@dataclass(frozen=True)
class WindowExpectation:
    """Arms the maintenance-window invariants (predictive planner).

    ``close_seconds`` is the window close (virtual seconds). The soak
    runner wires the state manager's ``window_audit`` hook to
    :meth:`InvariantMonitor.window_decision`, so the monitor holds the
    planner's admit/defer decision log ACROSS operator incarnations
    (the planner itself dies with each crash; its decisions must not).
    The monitor then asserts, from watch events plus that log:

    - **window-admission**: every node observed entering
      ``cordon-required`` must have a matching planner admit record
      whose conservatively predicted completion lands at/before the
      close — and nothing at all may be admitted once the close has
      passed. An admission with no record means the window gate was
      bypassed; a record crossing the close means the gate lied.
    - **window-stranded** (:meth:`InvariantMonitor.final_check`): at
      the end of the episode no node may sit mid-upgrade — every
      admitted node finished, every other node was deferred untouched
      in upgrade-required ("finish by the close or don't start",
      never started-and-stranded).
    """

    close_seconds: float


@dataclass(frozen=True)
class CapacityExpectation:
    """Arms the traffic-aware capacity-budget invariants.

    ``static_equivalent`` is the peak-safe STATIC budget a
    non-traffic-aware operator would have had to configure for the
    episode's worst observed demand (derived from the trace — see
    ``chaos/serving.DiurnalTrace.peak_utilization``). The budget soak
    runner feeds per-tick load/controller samples through
    :meth:`InvariantMonitor.capacity_sample`; the monitor asserts:

    - **capacity-slo**: at no tick does the offered load exceed what
      the admitting endpoints can place (``shortfall`` stays 0) — the
      controller left enough live capacity under every drain wave,
      spike and node kill;
    - **capacity-modulation** (:meth:`InvariantMonitor.final_check`):
      the effective budget was observed BOTH above and below the
      static equivalent during the episode — a controller that never
      crosses the static line in either direction is just a
      differently-spelled constant.

    The abort-residue check rides the always-on edge monitoring: every
    observed ``abort-required -> upgrade-required`` commit must leave
    the node schedulable (unless pre-cordoned) with no phase/wait/
    validation stamp — the patch is crash-atomic, so the event object
    itself must already be clean.

    With ``classes`` armed (name -> ``TrafficClassSpec``) the per-class
    teeth replace the strict aggregate SLO check:

    - **class-slo**: an interactive class's admission shortfall must be
      0 at every tick AND no interactive model may be operator-drained
      dark (zero admitting replicas with every host healthy) — batch
      classes may degrade within their ``maxShortfallFraction``;
    - **zero-drop** (armed via ``zero_drop``; enforced by the soak
      runner over the sim's exact per-session drop records): no
      operator-attributed dropped generation for ANY class.
    """

    static_equivalent: int
    require_modulation: bool = True
    classes: "Optional[dict]" = None
    zero_drop: bool = False


@dataclass(frozen=True)
class ShardExpectation:
    """Arms the sharded-control-plane invariants.

    Two of the three shard invariants are write-time properties the
    watch stream cannot attribute (events carry no writer identity), so
    the soak runner feeds them through explicit hooks; the monitor owns
    the bookkeeping, the verdicts and the report:

    - **shard-ownership** (:meth:`InvariantMonitor.audit_shard_write`):
      every durable node write must be issued by the replica that holds
      the node's shard Lease *at the instant of the write*, verified
      against the server-side Lease independently of the fencing layer
      under test. One out-of-partition write landing is a split brain.
    - **shard-takeover** (:meth:`InvariantMonitor.note_shard_orphaned` /
      :meth:`~InvariantMonitor.note_shard_resumed`): a killed replica's
      shards must be re-owned by a live replica within
      ``takeover_grace_seconds`` — orphaned partitions stalling past
      the grace is a liveness violation, and any shard still orphaned
      at :meth:`~InvariantMonitor.final_check` is too.
    - the **global budget** invariant needs no new machinery: the
      standing max-unavailable check stays armed fleet-wide, which is
      exactly what proves the durable budget shares never let two
      shards jointly overdraw (each replica only ever sees its own
      partition, yet the fleet-level inequality must hold at every
      admission instant, across takeovers included).
    """

    num_shards: int
    takeover_grace_seconds: float


@dataclass(frozen=True)
class DagExpectation:
    """Arms the multi-artifact upgrade-DAG invariants (policy/dag.py).

    - **dag-order** (always-on, event-sourced): no artifact advances
      on a node before its dependencies' durable stamps. Two edges are
      audited from the watch stream: a stamp annotation appearing (or
      changing) on a node requires every dependency's stamp to already
      be present on that node, and an artifact POD materializing at a
      NEW revision on a node requires the same — so neither the
      annotation nor the pod side of an advancement can jump the DAG,
      across operator crashes included. ``forbidden`` pins suffix
      containment: a (artifact, revision) pair that must never appear
      as a pod (the un-started dependent suffix of a quarantined
      artifact — its new revision may roll back, never forward).
    - **policy-sandbox** (fed by :meth:`InvariantMonitor.
      policy_sample`): the engine's registry must never accumulate an
      unaudited failure (every hook error/budget overrun produced a
      DecisionAudit record), and — runner-side — no exception may
      escape a reconcile while policy hooks are active (park, never
      wedge).
    """

    #: artifact name -> its dependency names.
    deps: "dict[str, tuple]"
    #: node-annotation key prefix of the revision stamps
    #: (UpgradeKeys.artifact_stamp_prefix).
    stamp_prefix: str
    #: pod "app" label value -> artifact name (pod attribution).
    apps: "dict[str, str]"
    #: namespace the artifact DaemonSets/pods live in.
    runtime_namespace: str = "tpu-system"
    #: (artifact, revision) pairs that must never run as a pod.
    forbidden: "tuple" = ()


@dataclass(frozen=True)
class InvariantViolation:
    """One broken safety property, with everything needed to replay it."""

    invariant: str
    at: float
    subject: str
    detail: str

    def describe(self) -> str:
        return (f"[t={self.at:g}] INVARIANT {self.invariant} violated on "
                f"{self.subject}: {self.detail}")


@dataclass
class _NodeMirror:
    upgrade_state: str = ""
    remediation_state: str = ""
    unschedulable: bool = False
    ready: bool = True
    pool: str = ""
    condemned: bool = False
    at_risk: bool = False


@dataclass
class InvariantMonitor:
    """Event-sourced safety checker for one chaos run."""

    cluster: FakeCluster
    upgrade_keys: UpgradeKeys
    remediation_keys: Optional[RemediationKeys] = None
    #: Upgrade-machine availability budget (int or "N%"); None disables
    #: the max-unavailable check (slice-planner runs).
    max_unavailable: Optional[IntOrString] = None
    #: Remediation availability budget; None disables its check.
    remediation_max_unavailable: Optional[IntOrString] = None
    #: maxParallelUpgrades; 0 disables the max-parallel check.
    max_parallel_upgrades: int = 0
    workload_namespace: str = "workloads"
    trace_limit: int = 4000
    watch_queue_bound: Optional[int] = None
    #: Arms the canary-halt/rollback invariants; None disables them.
    rollout: Optional[RolloutExpectation] = None
    #: Arms the slice-reconfiguration invariants; None disables them.
    reconfig: Optional[ReconfigExpectation] = None
    #: Arms the sharded-control-plane invariants; None disables them.
    shard: Optional[ShardExpectation] = None
    #: Arms the maintenance-window invariants; None disables them.
    window: Optional[WindowExpectation] = None
    #: Arms the capacity-budget invariants; None disables them.
    capacity: Optional[CapacityExpectation] = None
    #: Arms the artifact-DAG + policy-sandbox invariants; None
    #: disables them.
    dag: Optional[DagExpectation] = None
    #: Returns the CURRENT operator incarnation's
    #: OperatorObservability (rebound by the runner on restart). On any
    #: violation the monitor dumps the subject's audit slice + recent
    #: spans into the trace — "seed 7 failed" becomes a readable
    #: causal timeline. None = no dump.
    obs_source: Optional[Callable[[], object]] = None

    violations: list[InvariantViolation] = field(default_factory=list)
    trace: list[str] = field(default_factory=list)
    events_seen: int = 0
    watch_gaps: int = 0
    cordons_seen: int = 0
    uncordons_seen: int = 0
    #: condemned→slice-released durations observed (reconfig mode).
    remap_seconds: list[float] = field(default_factory=list)
    #: node writes audited against the shard Leases (shard mode).
    shard_writes_audited: int = 0
    #: orphaned→re-owned durations observed (shard mode).
    shard_takeover_seconds: list[float] = field(default_factory=list)

    def __post_init__(self) -> None:
        self._nodes: dict[str, _NodeMirror] = {}
        #: node -> revision hash of its runtime pod (rollout/reconfig).
        self._pod_revisions: dict[str, str] = {}
        #: distinct nodes seen failing ON the bad revision.
        self._bad_failed: set[str] = set()
        #: virtual time the failure threshold was first observed met.
        self.halt_evidence_at: Optional[float] = None
        #: True once a rollback signal (bad pod deleted / non-bad pod
        #: created after halt evidence) has been observed.
        self.rollback_signaled = False
        # -- reconfig mode bookkeeping --
        #: live pool membership mirrored from node labels.
        self._pool_members: dict[str, set[str]] = {}
        #: full shape per pool, learned at the INITIAL sync.
        self._pool_expected: dict[str, int] = {}
        #: original pool membership (initial sync) — anything added to a
        #: pool beyond this is a remapped spare.
        self._original_members: dict[str, set[str]] = {}
        #: nodes that joined a pool as a remapped spare.
        self._joined: set[str] = set()
        #: node -> virtual time its condemned annotation first appeared.
        self._condemned_at: dict[str, float] = {}
        #: node -> virtual time its at-risk stamp first appeared (the
        #: predictive arc's MTTR anchor: the remap races the still-
        #: ticking hardware from the VERDICT, not from a condemnation
        #: that only lands after the planned drain).
        self._at_risk_at: dict[str, float] = {}
        self._expected_armed = False
        # -- shard mode bookkeeping --
        #: shard -> virtual time it was orphaned (owner killed).
        self._shard_orphaned_at: dict[int, float] = {}
        # -- maintenance-window bookkeeping --
        #: node -> (decided_at, predicted_done) of the LATEST planner
        #: admit decision (window mode; survives incarnations because
        #: it lives here, not on the planner).
        self._window_admitted: dict[str, tuple[float, float]] = {}
        #: node -> decided_at of the latest planner defer decision.
        self._window_deferred: dict[str, float] = {}
        #: lifetime admit/defer decisions recorded (teeth evidence).
        self.window_admissions = 0
        self.window_deferrals = 0
        #: nodes observed in a DRAIN-PHASE state at the first event at
        #: or past the window close (None until the close is crossed):
        #: each must end the episode finished or ABORTED — never
        #: stranded mid-flight (the abort-not-strand extension).
        self._mid_drain_at_close: "Optional[set[str]]" = None
        # -- capacity-budget bookkeeping --
        #: abort-required -> upgrade-required commits observed (the
        #: abort arc's teeth evidence; residue-checked per event).
        self.aborts_observed = 0
        #: min/max effective budget seen via capacity_sample.
        self.capacity_effective_min: Optional[int] = None
        self.capacity_effective_max: Optional[int] = None
        self.capacity_samples = 0
        # -- decision-audit (always-on once a feed is wired) --
        #: True once note_decision has been wired as an audit mirror:
        #: every observed admission/abort edge must then have a
        #: matching DecisionAudit record. The log lives HERE (not on
        #: the recorder) so it survives operator incarnations — the
        #: window-soak decision-log idiom.
        self._decision_feed = False
        #: node -> virtual time of its latest "admit" record.
        self._admit_decided_at: dict[str, float] = {}
        #: node -> virtual time of its latest "abort" record.
        self._abort_decided_at: dict[str, float] = {}
        #: node -> virtual time it last ENTERED upgrade-required (the
        #: anchor a fresh admission's record must postdate).
        self._required_entered_at: dict[str, float] = {}
        #: lifetime decisions mirrored (teeth evidence).
        self.decisions_recorded = 0
        #: explain() probes run / found empty (teeth evidence).
        self.explains_probed = 0
        # -- artifact-DAG + policy-sandbox bookkeeping (dag mode) --
        #: node -> artifact -> last seen revision stamp (from node
        #: annotation events; survives operator incarnations).
        self._artifact_stamps: "dict[str, dict[str, str]]" = {}
        #: (artifact, node) -> last seen pod revision hash.
        self._artifact_pod_rev: "dict[tuple, str]" = {}
        #: dag-order edges audited (teeth evidence).
        self.dag_stamps_seen = 0
        self.dag_advances_seen = 0
        #: policy_sample() probes run (teeth evidence).
        self.policy_samples = 0
        #: preflight_sample() probes run (teeth evidence).
        self.preflight_samples = 0
        # delay_exempt: the auditor's stream stays live through a
        # watch-delay fault window — the SYSTEM under test sees the
        # lag, the monitor judging it must see ground truth (a lagged
        # mirror would emit false budget/placement verdicts about
        # transitions that already healed)
        self._watch = self.cluster.watch(max_queue=self.watch_queue_bound,
                                         delay_exempt=True)
        self.resync("initial sync")

    def _mirror_of(self, node) -> _NodeMirror:
        labels = node.metadata.labels
        return _NodeMirror(
            upgrade_state=labels.get(self.upgrade_keys.state_label, ""),
            remediation_state=(labels.get(
                self.remediation_keys.state_label, "")
                if self.remediation_keys else ""),
            unschedulable=node.is_unschedulable(),
            ready=node.is_ready(),
            pool=labels.get(GKE_NODEPOOL_LABEL, ""),
            condemned=(self.remediation_keys is not None
                       and self.remediation_keys.condemned_annotation
                       in node.metadata.annotations),
            at_risk=(self.remediation_keys is not None
                     and self.remediation_keys.at_risk_annotation
                     in node.metadata.annotations))

    # -- plumbing ---------------------------------------------------------
    def _now(self) -> float:
        return self.cluster.clock.now()

    def _record(self, line: str) -> None:
        self.trace.append(f"[t={self._now():g}] {line}")
        if len(self.trace) > self.trace_limit:
            # keep the tail; the head is summarized by its loss
            del self.trace[:len(self.trace) - self.trace_limit]

    def _violate(self, invariant: str, subject: str, detail: str) -> None:
        violation = InvariantViolation(invariant, self._now(), subject,
                                       detail)
        self.violations.append(violation)
        self._record(violation.describe())
        logger.error("%s", violation.describe())
        self._dump_obs_context(subject)

    def _dump_obs_context(self, subject: str) -> None:
        """On a violation, fold the relevant DecisionAudit slice and
        journey spans into the trace: the report stops being "seed 7
        failed" and becomes the causal timeline that produced the bad
        edge. Best-effort — a broken obs layer must never mask the
        violation it is annotating."""
        if self.obs_source is None:
            return
        try:
            obs = self.obs_source()
        except Exception:  # noqa: BLE001 — diagnostic only
            return
        if obs is None:
            return
        try:
            for kind, rec in sorted(obs.audit.latest_fleet().items()):
                self._record(f"  audit[fleet/{kind}]: {rec.describe()}")
            for rec in reversed(obs.audit.records_for(subject, limit=6)):
                self._record(f"  audit[{subject}]: {rec.describe()}")
            for journey in obs.tracer.spans_for(subject, limit=1):
                self._record(
                    f"  trace[{subject}] {journey['traceId']} "
                    f"({journey['outcome']}): " + " -> ".join(
                        f"{span['name']}@{span['startSeconds']:g}"
                        for span in journey["spans"]))
        except Exception:  # noqa: BLE001 — diagnostic only
            logger.debug("obs context dump failed", exc_info=True)

    def resync(self, why: str) -> None:
        """Rebuild the node mirror from a fresh list, assertion-free (a
        stream gap hides an unknown number of intermediate states — the
        same blind spot an informer relist has)."""
        self._record(f"resync ({why})")
        nodes = consume_transient(self.cluster.list_nodes)
        fresh: dict[str, _NodeMirror] = {}
        for node in nodes:
            fresh[node.metadata.name] = self._mirror_of(node)
        self._nodes = fresh
        if self.reconfig is not None:
            members: dict[str, set[str]] = {}
            for name, mirror in fresh.items():
                if mirror.pool:
                    members.setdefault(mirror.pool, set()).add(name)
                if mirror.condemned:
                    self._condemned_at.setdefault(name, self._now())
                if mirror.at_risk:
                    self._at_risk_at.setdefault(name, self._now())
            self._pool_members = members
            if not self._expected_armed:
                # the initial sync defines each slice's full shape
                self._pool_expected = {pool: len(names)
                                       for pool, names in members.items()}
                self._original_members = {pool: set(names)
                                          for pool, names in members.items()}
                self._expected_armed = True
            else:
                # joins hidden by a watch gap are absorbed (no
                # assertions) but still tracked for the cordon-after-
                # join check
                for pool, names in members.items():
                    extra = names - self._original_members.get(pool, set())
                    self._joined.update(extra)
        if self.dag is not None:
            # re-seed the stamp + pod-revision mirrors from live state:
            # like the node mirror, a stream gap absorbs unknown
            # intermediate states assertion-free
            stamps: "dict[str, dict[str, str]]" = {}
            for node in nodes:
                per_node = {}
                for artifact in self.dag.deps:
                    value = node.metadata.annotations.get(
                        self.dag.stamp_prefix + artifact)
                    if value:
                        per_node[artifact] = value
                if per_node:
                    stamps[node.metadata.name] = per_node
            self._artifact_stamps = stamps
            dag_pods = consume_transient(lambda: self.cluster.list_pods(
                namespace=self.dag.runtime_namespace))
            for pod in dag_pods:
                artifact = self.dag.apps.get(
                    pod.metadata.labels.get("app", ""))
                pod_hash = pod.metadata.labels.get(
                    POD_CONTROLLER_REVISION_HASH_LABEL)
                if artifact and pod_hash and pod.spec.node_name:
                    self._artifact_pod_rev[(artifact,
                                            pod.spec.node_name)] = pod_hash
        runtime_ns = None
        if self.rollout is not None:
            runtime_ns = self.rollout.runtime_namespace
        elif self.reconfig is not None:
            runtime_ns = self.reconfig.runtime_namespace
        if runtime_ns is not None:
            pods = consume_transient(lambda: self.cluster.list_pods(
                namespace=runtime_ns))
            revisions: dict[str, str] = {}
            for pod in pods:
                pod_hash = pod.metadata.labels.get(
                    POD_CONTROLLER_REVISION_HASH_LABEL)
                if pod_hash and pod.spec.node_name:
                    revisions[pod.spec.node_name] = pod_hash
            self._pod_revisions = revisions

    def drain(self) -> int:
        """Consume every pending watch event; returns events processed.
        Call between mutation batches (the runner does, after each
        reconcile and each virtual-clock step)."""
        processed = 0
        if self.window is not None and self._mid_drain_at_close is None \
                and self._now() >= self.window.close_seconds:
            # the close just passed: snapshot every node still in a
            # drain-phase state — each must finish or ABORT by episode
            # end, never strand (checked in final_check)
            drain_phase = frozenset(str(s) for s in ABORTABLE_STATES)
            self._mid_drain_at_close = {
                name for name, mirror in self._nodes.items()
                if mirror.upgrade_state in drain_phase}
            if self._mid_drain_at_close:
                self._record(
                    f"window close crossed with "
                    f"{len(self._mid_drain_at_close)} node(s) still "
                    f"mid-drain: {sorted(self._mid_drain_at_close)} — "
                    f"each must abort or finish, never strand")
        while True:
            if self._watch.stopped:
                # the watch-break fault closed our stream: resubscribe
                # and relist, like any informer whose server hung up
                self.watch_gaps += 1
                self._watch = self.cluster.watch(
                    max_queue=self.watch_queue_bound, delay_exempt=True)
                self.resync("watch stream dropped")
            event = self._watch.get(timeout=0.0)
            if event is None:
                if self._watch.stopped:
                    continue  # stopped between get() calls: resubscribe
                return processed
            processed += 1
            self.events_seen += 1
            if event.type == BOOKMARK:
                # bounded-queue overflow: events were dropped
                self.watch_gaps += 1
                self.resync("watch queue overflow (BOOKMARK)")
                continue
            if event.kind == KIND_NODE:
                self._on_node(event.type, event.object)
            elif event.kind == KIND_POD:
                self._on_pod(event.type, event.object)

    # -- node events ------------------------------------------------------
    def _on_node(self, event_type: str, node) -> None:
        name = node.metadata.name
        if event_type == DELETED:
            gone = self._nodes.pop(name, None)
            self._record(f"node {name} deleted")
            if self.dag is not None:
                # a killed node takes its stamps and pods with it
                self._artifact_stamps.pop(name, None)
                for key in [k for k in self._artifact_pod_rev
                            if k[1] == name]:
                    del self._artifact_pod_rev[key]
            if self.reconfig is not None and gone is not None \
                    and gone.pool:
                self._pool_members.get(gone.pool, set()).discard(name)
                self._check_slice_shape(gone.pool)
            return
        new = self._mirror_of(node)
        old = self._nodes.get(name)
        if old is None:
            self._nodes[name] = new
            if self.reconfig is not None and new.pool:
                self._pool_members.setdefault(new.pool, set()).add(name)
            if self.dag is not None:
                self._check_dag_stamps(name, node)
            self._record(f"node {name} added "
                         f"(upgrade={new.upgrade_state or 'unknown'})")
            return
        if old.unschedulable != new.unschedulable:
            if new.unschedulable:
                self.cordons_seen += 1
                self._record(f"node {name} cordoned")
                if self.reconfig is not None and name in self._joined:
                    self._violate(
                        "reconfig-joint-plan", name,
                        "remapped spare cordoned AFTER joining its "
                        "slice — the joint plan owed it exactly one "
                        "cordon/drain cycle, taken while it was still "
                        "out of the slice")
            else:
                self.uncordons_seen += 1
                self._record(f"node {name} uncordoned")
        if old.ready != new.ready:
            self._record(f"node {name} ready={new.ready}")
        # commit the new mirror BEFORE budget math so counts include
        # this very transition ("at any instant" includes the instant
        # the admission label lands)
        self._nodes[name] = new
        if self.reconfig is not None:
            if not old.condemned and new.condemned:
                self._condemned_at.setdefault(name, self._now())
                self._record(f"node {name} condemned")
            if not old.at_risk and new.at_risk:
                self._at_risk_at.setdefault(name, self._now())
                self._record(f"node {name} condemned at-risk "
                             f"(precursor)")
            if old.pool != new.pool:
                self._on_pool_change(name, old, new)
        if self.dag is not None:
            self._check_dag_stamps(name, node)
        if old.upgrade_state != new.upgrade_state:
            self._record(f"node {name} upgrade "
                         f"{old.upgrade_state or 'unknown'} -> "
                         f"{new.upgrade_state or 'unknown'}")
            self._check_upgrade_edge(name, old, new)
            self._check_abort_residue(name, old, new, node)
            self._check_decision_audit(name, old, new)
            self._track_rollout_verdict(name, new)
        if old.remediation_state != new.remediation_state:
            self._record(f"node {name} remediation "
                         f"{old.remediation_state or 'healthy'} -> "
                         f"{new.remediation_state or 'healthy'}")
            self._check_remediation_edge(name, old, new)

    # -- artifact-DAG + policy-sandbox invariants -------------------------
    def _check_dag_stamps(self, name: str, node) -> None:
        """dag-order, stamp side: a revision stamp appearing (or
        changing) on a node requires every dependency's stamp to be
        present on the node at that instant — stamps are written one
        patch each in dependency order, so a crash can truncate the
        sequence but never reorder it."""
        dag = self.dag
        annotations = node.metadata.annotations
        current: "dict[str, str]" = {}
        for artifact in dag.deps:
            value = annotations.get(dag.stamp_prefix + artifact)
            if value:
                current[artifact] = value
        previous = self._artifact_stamps.get(name, {})
        for artifact, revision in current.items():
            if previous.get(artifact) == revision:
                continue
            self.dag_stamps_seen += 1
            missing = [dep for dep in dag.deps.get(artifact, ())
                       if not current.get(dep)]
            if missing:
                self._violate(
                    "dag-order", name,
                    f"artifact {artifact} stamped at {revision!r} "
                    f"before dependency stamp(s) {missing} — the "
                    f"crash-ordered prefix property is broken")
            else:
                self._record(f"node {name} artifact {artifact} "
                             f"stamped {revision}")
        self._artifact_stamps[name] = current

    def _on_dag_pod(self, event_type: str, pod) -> None:
        """dag-order, pod side: an artifact pod materializing at a NEW
        revision on a node requires the dependencies' stamps on that
        node (the coordinator only deletes-for-upgrade under satisfied
        deps, and the DS controller recreates at the target) — plus
        the suffix-containment pin (``forbidden`` revisions never
        run)."""
        dag = self.dag
        artifact = dag.apps.get(pod.metadata.labels.get("app", ""))
        if artifact is None:
            return
        revision = pod.metadata.labels.get(
            POD_CONTROLLER_REVISION_HASH_LABEL)
        node_name = pod.spec.node_name
        if not revision or not node_name or event_type == DELETED:
            return
        where = f"pod {pod.metadata.namespace}/{pod.metadata.name}"
        for bad_artifact, bad_revision in dag.forbidden:
            if artifact == bad_artifact and revision == bad_revision:
                self._violate(
                    "dag-order", where,
                    f"artifact {artifact} ran revision {revision!r} — "
                    f"the un-started dependent suffix of a quarantined "
                    f"artifact must roll back, never forward")
        key = (artifact, node_name)
        previous = self._artifact_pod_rev.get(key)
        self._artifact_pod_rev[key] = revision
        if event_type != ADDED or previous is None \
                or previous == revision:
            return
        self.dag_advances_seen += 1
        stamps = self._artifact_stamps.get(node_name, {})
        missing = [dep for dep in dag.deps.get(artifact, ())
                   if not stamps.get(dep)]
        if missing:
            self._violate(
                "dag-order", where,
                f"artifact {artifact} advanced {previous!r} -> "
                f"{revision!r} on node {node_name} before dependency "
                f"stamp(s) {missing}")
        else:
            self._record(f"artifact {artifact} advanced {previous} -> "
                         f"{revision} on {node_name}")

    def policy_sample(self, stats: "Optional[dict]") -> None:
        """One runner probe of the live engine's registry counters
        (policy-sandbox): every hook failure must have produced an
        audit record — an unaudited failure means the sandbox parked
        silently, which is the observability gap the invariant
        exists to close."""
        if stats is None:
            return
        self.policy_samples += 1
        unaudited = stats.get("unauditedFailures", 0)
        if unaudited:
            self._violate(
                "policy-sandbox", "engine",
                f"{unaudited} hook failure(s) produced no DecisionAudit "
                f"record (stats: {stats})")

    def preflight_sample(self, stats: "Optional[dict]") -> None:
        """One runner probe of the preflight forecaster's read-only
        evidence counters (preflight-readonly): the what-if replay runs
        against a FROZEN clone, so ANY write that reached the clone —
        or any live-cluster mutation observed across a forecast — means
        the simulation leaked into reality. The counters are lifetime
        totals; a single nonzero reading condemns the whole episode."""
        if stats is None:
            return
        self.preflight_samples += 1
        frozen_writes = stats.get("frozenWriteAttempts", 0)
        if frozen_writes:
            self._violate(
                "preflight-readonly", "forecaster",
                f"{frozen_writes} write attempt(s) reached the frozen "
                f"preflight clone (stats: {stats})")
        live_mutations = stats.get("liveMutations", 0)
        if live_mutations:
            self._violate(
                "preflight-readonly", "forecaster",
                f"{live_mutations} live-cluster mutation(s) observed "
                f"during preflight forecasting (stats: {stats})")

    # -- slice-reconfiguration invariants ---------------------------------
    def _degraded_lost(self, pool: str) -> int:
        """Hosts of ``pool`` durably admitted as lost (degraded-slices
        DaemonSet annotation). Read lazily — only when a shape check
        needs it."""
        from tpu_operator_libs.topology.slice_topology import (
            decode_degraded_slices,
        )

        assert self.reconfig is not None
        key = self.reconfig.topology_keys.degraded_slices_annotation
        daemon_sets = consume_transient(lambda: self.cluster.list_daemon_sets(
            self.reconfig.runtime_namespace))
        lost: set[str] = set()
        for ds in daemon_sets:
            lost.update(decode_degraded_slices(
                ds.metadata.annotations.get(key, "")).get(pool, ()))
        return len(lost)

    def _check_slice_shape(self, pool: str) -> None:
        """A slice may only be short of its learned full shape by hosts
        the degraded record declares lost — anything else is a silently
        short placement."""
        expected = self._pool_expected.get(pool)
        if expected is None:
            return  # pool born after arming (not a managed slice shape)
        have = len(self._pool_members.get(pool, ()))
        if have >= expected:
            return
        allowed = expected - self._degraded_lost(pool)
        if have < allowed:
            self._violate(
                "slice-placement", pool,
                f"slice has {have} host(s), expected {expected} with "
                f"{expected - allowed} declared lost — a member was "
                f"removed without a spare remap or a degraded "
                f"admission (silently short placement)")

    def _on_pool_change(self, name: str, old: _NodeMirror,
                        new: _NodeMirror) -> None:
        reconfig = self.reconfig
        if old.pool:
            self._pool_members.get(old.pool, set()).discard(name)
        if new.pool:
            self._pool_members.setdefault(new.pool, set()).add(name)
        self._record(f"node {name} pool "
                     f"{old.pool or '-'} -> {new.pool or '-'}")
        if new.pool and name not in self._original_members.get(
                new.pool, set()):
            # a remapped spare joined: the joint plan must have finished
            # its upgrade (target revision, schedulable) BEFORE the join
            self._joined.add(name)
            revision = self._pod_revisions.get(name)
            if revision != reconfig.target_revision:
                self._violate(
                    "reconfig-joint-plan", name,
                    f"spare joined slice {new.pool} with runtime pod on "
                    f"revision {revision!r}, not the target "
                    f"{reconfig.target_revision!r} — it must be "
                    f"upgraded while still OUT of the slice")
            if new.unschedulable:
                self._violate(
                    "reconfig-joint-plan", name,
                    f"spare joined slice {new.pool} while cordoned")
        if old.pool and not new.pool:
            # release: the shape must already be whole (spare joined
            # first) or declared degraded
            self._check_slice_shape(old.pool)
            condemned_at = self._condemned_at.get(name)
            if condemned_at is None:
                # predictive arc: the slice is released while the node
                # still serves — the at-risk verdict is the anchor
                condemned_at = self._at_risk_at.get(name)
            if condemned_at is not None:
                self.remap_seconds.append(self._now() - condemned_at)
                self._record(
                    f"slice {old.pool} released from condemned node "
                    f"{name} after {self._now() - condemned_at:g}s")

    def _track_rollout_verdict(self, name: str,
                               new: _NodeMirror) -> None:
        """Accumulate bad-revision failure verdicts the monitor has
        OBSERVED (its own evidence, independent of the guard's)."""
        if self.rollout is None or self.halt_evidence_at is not None:
            return
        if new.upgrade_state != str(UpgradeState.FAILED):
            return
        if self._pod_revisions.get(name) != self.rollout.bad_revision:
            return
        self._bad_failed.add(name)
        if len(self._bad_failed) >= self.rollout.failure_threshold:
            self.halt_evidence_at = self._now()
            self._record(
                f"rollout halt evidence: {len(self._bad_failed)} "
                f"node(s) failed on revision "
                f"{self.rollout.bad_revision!r} — admissions must stop "
                f"until a rollback signal")

    def _check_upgrade_edge(self, name: str, old: _NodeMirror,
                            new: _NodeMirror) -> None:
        legal = LEGAL_EDGES.get(old.upgrade_state, frozenset())
        if new.upgrade_state not in legal:
            self._violate(
                "legal-transition", name,
                f"upgrade {old.upgrade_state or 'unknown'!r} -> "
                f"{new.upgrade_state or 'unknown'!r} is not an edge of "
                f"consts.STATE_EDGES")
            return
        if (self.rollout is not None
                and self.halt_evidence_at is not None
                and not self.rollback_signaled
                and new.upgrade_state in (
                    str(UpgradeState.CORDON_REQUIRED),
                    str(UpgradeState.UPGRADE_REQUIRED))):
            self._violate(
                "rollout-halt", name,
                f"node moved to {new.upgrade_state!r} after the canary "
                f"failure threshold was met (at t="
                f"{self.halt_evidence_at:g}) and before any rollback "
                f"signal — the fleet failed to halt")
        if new.upgrade_state != str(UpgradeState.CORDON_REQUIRED):
            return
        if self.window is not None:
            self._check_window_admission(name)
        if old.unschedulable:
            return  # manual-cordon override: admission is budget-free
        total = len(self._nodes)
        if self.max_unavailable is not None and total:
            budget = scaled_value_from_int_or_percent(
                self.max_unavailable, total, round_up=True)
            unavailable = sum(
                1 for m in self._nodes.values()
                if m.unschedulable or not m.ready)
            committed = sum(
                1 for m in self._nodes.values()
                if m.upgrade_state == str(UpgradeState.CORDON_REQUIRED))
            if unavailable + committed > budget:
                self._violate(
                    "max-unavailable", name,
                    f"admission makes {unavailable} unavailable + "
                    f"{committed} committed-to-cordon > budget {budget} "
                    f"(maxUnavailable={self.max_unavailable!r}, "
                    f"total={total})")
        if self.max_parallel_upgrades > 0:
            in_progress = sum(
                1 for m in self._nodes.values()
                if m.upgrade_state in _IN_PROGRESS)
            if in_progress > self.max_parallel_upgrades:
                self._violate(
                    "max-parallel", name,
                    f"{in_progress} upgrades in progress > "
                    f"maxParallelUpgrades={self.max_parallel_upgrades}")

    def _check_remediation_edge(self, name: str, old: _NodeMirror,
                                new: _NodeMirror) -> None:
        legal = REMEDIATION_LEGAL_EDGES.get(old.remediation_state,
                                            frozenset())
        if new.remediation_state not in legal:
            self._violate(
                "legal-transition", name,
                f"remediation {old.remediation_state or 'healthy'!r} -> "
                f"{new.remediation_state or 'healthy'!r} is not an edge "
                f"of consts.REMEDIATION_EDGES")
            return
        if new.remediation_state != str(RemediationState.CORDON_REQUIRED):
            return
        live = new.ready and not new.unschedulable
        if not live:
            return  # dead nodes are budget-exempt (already unavailable)
        total = len(self._nodes)
        if self.remediation_max_unavailable is None or not total:
            return
        budget = scaled_value_from_int_or_percent(
            self.remediation_max_unavailable, total, round_up=True)
        unavailable = sum(1 for m in self._nodes.values()
                          if m.unschedulable or not m.ready)
        live_committed = sum(
            1 for m in self._nodes.values()
            if m.remediation_state
            == str(RemediationState.CORDON_REQUIRED)
            and m.ready and not m.unschedulable)
        if unavailable + live_committed > budget:
            self._violate(
                "max-unavailable", name,
                f"remediation admission makes {unavailable} unavailable "
                f"+ {live_committed} live committed-to-cordon > budget "
                f"{budget} (maxUnavailable="
                f"{self.remediation_max_unavailable!r}, total={total})")

    # -- mid-flight abort invariants --------------------------------------
    def _check_abort_residue(self, name: str, old: _NodeMirror,
                             new: _NodeMirror, node: "object") -> None:
        """An observed ``abort-required -> upgrade-required`` commit
        must already be residue-free AT THE EVENT INSTANT: the abort's
        annotation deletions ride the same merge patch as the label,
        and the uncordon precedes it — so a dirty event means the
        crash-atomicity claim is false, not merely that cleanup is
        late. Always armed (the edge only exists when the abort arc
        ran)."""
        if old.upgrade_state != str(UpgradeState.ABORT_REQUIRED) \
                or new.upgrade_state != str(UpgradeState.UPGRADE_REQUIRED):
            return
        self.aborts_observed += 1
        keys = self.upgrade_keys
        annotations = node.metadata.annotations
        residue = sorted(
            key for key in (keys.phase_start_annotation,
                            keys.pod_completion_start_annotation,
                            keys.validation_start_annotation)
            if key in annotations)
        if residue:
            self._violate(
                "abort-residue", name,
                f"abort committed back to upgrade-required with "
                f"bookkeeping still stamped: {residue}")
        if new.unschedulable \
                and keys.initial_state_annotation not in annotations:
            self._violate(
                "abort-residue", name,
                "abort committed back to upgrade-required with the "
                "node still cordoned (and no pre-upgrade cordon "
                "memory) — the uncordon was skipped")

    # -- capacity-budget invariants ---------------------------------------
    def capacity_sample(self, load: dict,
                        status: Optional[dict]) -> None:
        """One replay tick's load/controller sample (budget soak runner
        hook): ``load`` from ``ServingFleetSim.tick``, ``status`` the
        CapacityBudgetController's ``last_status`` (None before its
        first evaluation). The SLO check is strict — a single tick of
        unplaced offered load is a breach."""
        if self.capacity is None:
            return
        self.capacity_samples += 1
        classes = self.capacity.classes
        if classes:
            # per-class teeth: strict for interactive, relaxed for
            # batch — the aggregate strict check would mis-flag the
            # batch degradation the class SLOs deliberately allow
            for cls, cell in sorted(
                    (load.get("perClass") or {}).items()):
                spec = classes.get(cls)
                allowed = 0.0
                if spec is not None and not spec.interactive:
                    allowed = (spec.max_shortfall_fraction
                               * cell["target"])
                # overload/fault excuse: shortfall beyond what even a
                # perfect (undrained, fault-dead-excluded) fleet could
                # have served is not a drain decision
                ref = cell.get("refCapacity")
                if ref is not None:
                    allowed += max(0, cell["target"] - ref)
                if cell["shortfall"] > allowed:
                    strict = spec is not None and spec.interactive
                    kind = "strict interactive" if strict \
                        else "relaxed"
                    self._violate(
                        "class-slo", f"class {cls}",
                        f"offered load {cell['target']} exceeded "
                        f"placed {cell['inFlight']} by "
                        f"{cell['shortfall']} generation(s) at t="
                        f"{load['now']:g} (allowed {allowed:g}) — "
                        f"the {kind} class SLO was breached")
            dark = load.get("interactiveDarkOperator", 0)
            if dark:
                self._violate(
                    "class-slo", "fleet",
                    f"{dark} interactive model(s) drained DARK by the "
                    f"operator at t={load['now']:g} (zero admitting "
                    f"replicas with every host healthy) — the "
                    f"sole-replica hold / prewarm arc was bypassed")
        elif load.get("shortfall", 0) > 0:
            self._violate(
                "capacity-slo", "fleet",
                f"offered load {load['target']} exceeded admitting "
                f"capacity {load['admittingCapacity']} by "
                f"{load['shortfall']} generation(s) at t="
                f"{load['now']:g} — the effective budget left too "
                f"little live capacity")
        if status is not None:
            eff = status["effectiveBudget"]
            self.capacity_effective_min = (
                eff if self.capacity_effective_min is None
                else min(self.capacity_effective_min, eff))
            self.capacity_effective_max = (
                eff if self.capacity_effective_max is None
                else max(self.capacity_effective_max, eff))

    # -- maintenance-window invariants ------------------------------------
    def window_decision(self, kind: str, node: str, at: float,
                        predicted_done: float) -> None:
        """One planner window decision (wired as the state manager's
        ``window_audit`` hook): ``kind`` is ``"admit"`` or ``"defer"``;
        ``predicted_done`` the planner's CONSERVATIVE predicted
        completion instant for the node at decision time."""
        if self.window is None:
            return
        if kind == "admit":
            self._window_admitted[node] = (at, predicted_done)
            self.window_admissions += 1
            self._record(
                f"window admit {node}: predicted done t="
                f"{predicted_done:g} (close t="
                f"{self.window.close_seconds:g})")
        else:
            self._window_deferred[node] = at
            self.window_deferrals += 1
            self._record(
                f"window defer {node}: predicted done t="
                f"{predicted_done:g} would cross close t="
                f"{self.window.close_seconds:g}")

    def _check_window_admission(self, name: str) -> None:
        """A node was observed entering cordon-required under an armed
        window expectation: the planner must have recorded a compliant
        admit decision for it."""
        close = self.window.close_seconds
        now = self._now()
        if now >= close:
            self._violate(
                "window-admission", name,
                f"node started upgrading at t={now:g}, at/after the "
                f"maintenance-window close t={close:g}")
            return
        record = self._window_admitted.get(name)
        if record is None:
            self._violate(
                "window-admission", name,
                "node entered cordon-required with no planner admit "
                "record — the maintenance-window gate was bypassed")
            return
        _, predicted_done = record
        if predicted_done > close:
            self._violate(
                "window-admission", name,
                f"node admitted although its predicted completion t="
                f"{predicted_done:g} crosses the window close t="
                f"{close:g}")

    # -- decision-audit invariants (obs/) ---------------------------------
    def note_decision(self, record: "object") -> None:
        """One DecisionAudit record (wired as the audit's ``mirror``
        by the runner, per incarnation). The monitor-held log survives
        operator crashes, so the edge audit below never blames a fresh
        incarnation for a predecessor's decision. Arms the
        decision-audit invariant on first wiring."""
        self._decision_feed = True
        self.decisions_recorded += 1
        if record.kind == "admit":
            self._admit_decided_at[record.node] = record.at
        elif record.kind == "abort":
            self._abort_decided_at[record.node] = record.at

    def parked_nodes(self) -> "list[str]":
        """Nodes not upgrade-done per the mirror (the explain probe's
        subject list — read from the mirror, not the cluster, so the
        probe never trips on an injected API fault)."""
        done = str(UpgradeState.DONE)
        return [name for name, mirror in sorted(self._nodes.items())
                if mirror.upgrade_state != done]

    def audit_explain(self, name: str, result: "object") -> None:
        """One explain() probe result: every parked node must produce
        a non-empty blocking-reason chain — a silent explain IS the
        observability gap this layer exists to close."""
        self.explains_probed += 1
        chain = (result or {}).get("blocking") \
            if isinstance(result, dict) else None
        if not chain:
            self._violate(
                "explain-empty", name,
                f"explain() returned no blocking-reason chain for a "
                f"parked node (result: {result!r})")
        else:
            self._record(f"explain {name}: {chain[0]}")

    def _check_decision_audit(self, name: str, old: _NodeMirror,
                              new: _NodeMirror) -> None:
        """Every observed admission (upgrade-required→cordon-required)
        and abort (→abort-required) edge must have a matching audit
        record no older than the node's last entry into the source
        state — armed once a decision feed is wired."""
        if not self._decision_feed:
            return
        if new.upgrade_state == str(UpgradeState.CORDON_REQUIRED) \
                and old.upgrade_state \
                == str(UpgradeState.UPGRADE_REQUIRED):
            decided = self._admit_decided_at.get(name)
            anchor = self._required_entered_at.get(name, 0.0)
            if decided is None or decided < anchor:
                self._violate(
                    "decision-audit", name,
                    f"admission edge observed with no matching "
                    f"DecisionAudit admit record (last admit: "
                    f"{decided}, entered upgrade-required: {anchor:g})")
        elif new.upgrade_state == str(UpgradeState.ABORT_REQUIRED):
            decided = self._abort_decided_at.get(name)
            anchor = self._admit_decided_at.get(name, 0.0)
            if decided is None or decided < anchor:
                self._violate(
                    "decision-audit", name,
                    f"abort edge observed with no matching "
                    f"DecisionAudit abort record (last abort "
                    f"decision: {decided})")
        if new.upgrade_state == str(UpgradeState.UPGRADE_REQUIRED):
            self._required_entered_at[name] = self._now()

    # -- sharded-control-plane invariants ---------------------------------
    def audit_shard_write(self, node_name: str, shard: int,
                          writer: str, holder: str) -> None:
        """One durable node write, audited against the server-side shard
        Lease at the instant it was issued (the runner's audit client
        calls this independently of the fencing layer under test).
        ``holder`` is the Lease's holder at write time; a mismatch means
        an out-of-partition write LANDED — the split brain the fencing
        check exists to make impossible."""
        if self.shard is None:
            return
        self.shard_writes_audited += 1
        if writer != holder:
            self._violate(
                "shard-ownership", node_name,
                f"durable write by replica {writer!r} landed while "
                f"shard {shard}'s lease was held by {holder!r} — an "
                f"out-of-partition write (split brain)")

    def note_shard_orphaned(self, shard: int, at: float) -> None:
        """A replica died holding ``shard`` (runner hook)."""
        if self.shard is None:
            return
        self._shard_orphaned_at.setdefault(shard, at)
        self._record(f"shard {shard} orphaned (owner killed)")

    def orphaned_shards(self) -> "tuple[int, ...]":
        """Shards currently orphaned (killed owner, no live successor
        observed yet) — the runner polls this to detect resumes."""
        return tuple(sorted(self._shard_orphaned_at))

    def suspend_orphan_clock(self, seconds: float) -> None:
        """Exclude ``seconds`` from every orphaned shard's takeover
        clock (runner hook, called for windows with ZERO live
        replicas). The takeover invariant bounds how long the SYSTEM
        leaves an adoptable shard ownerless — time in which no replica
        exists to adopt anything measures the fault schedule, not the
        control plane."""
        if self.shard is None:
            return
        for shard in self._shard_orphaned_at:
            self._shard_orphaned_at[shard] += seconds

    def note_shard_resumed(self, shard: int) -> None:
        """``shard``'s Lease is held by a live replica again (runner
        hook). Violates shard-takeover when the orphan window exceeded
        the configured grace."""
        if self.shard is None:
            return
        orphaned_at = self._shard_orphaned_at.pop(shard, None)
        if orphaned_at is None:
            return
        elapsed = self._now() - orphaned_at
        self.shard_takeover_seconds.append(elapsed)
        self._record(f"shard {shard} resumed after {elapsed:g}s orphaned")
        if elapsed > self.shard.takeover_grace_seconds:
            self._violate(
                "shard-takeover", f"shard {shard}",
                f"orphaned shard resumed only after {elapsed:g}s — "
                f"past the {self.shard.takeover_grace_seconds:g}s "
                f"takeover grace (a dead replica's partition stalled)")

    # -- pod events -------------------------------------------------------
    def _on_pod(self, event_type: str, pod) -> None:
        if (self.dag is not None and pod.metadata.namespace
                == self.dag.runtime_namespace):
            self._on_dag_pod(event_type, pod)
            # fall through: rollout/reconfig mirrors may share the
            # namespace when armed together
        if (self.rollout is not None and pod.metadata.namespace
                == self.rollout.runtime_namespace):
            self._on_runtime_pod(event_type, pod)
            return
        if (self.reconfig is not None and pod.metadata.namespace
                == self.reconfig.runtime_namespace):
            # per-node revision mirror feeding the joint-plan check;
            # runtime DS pods legally land on cordoned nodes
            pod_hash = pod.metadata.labels.get(
                POD_CONTROLLER_REVISION_HASH_LABEL)
            node_name = pod.spec.node_name
            if pod_hash and node_name:
                if event_type == DELETED:
                    if self._pod_revisions.get(node_name) == pod_hash:
                        del self._pod_revisions[node_name]
                else:
                    self._pod_revisions[node_name] = pod_hash
            return
        if event_type != ADDED:
            return
        if pod.metadata.namespace != self.workload_namespace:
            return  # DaemonSet runtime pods legally land on cordoned nodes
        node_name = pod.spec.node_name
        mirror = self._nodes.get(node_name) if node_name else None
        if mirror is None:
            return
        where = f"pod {pod.metadata.namespace}/{pod.metadata.name}"
        self._record(f"{where} scheduled on {node_name}")
        if mirror.unschedulable:
            self._violate(
                "workload-placement", where,
                f"scheduled onto cordoned node {node_name}")
        if mirror.upgrade_state in WORKLOAD_UNSAFE_STATES:
            self._violate(
                "workload-placement", where,
                f"scheduled onto node {node_name} in mid-upgrade state "
                f"{mirror.upgrade_state!r}")
        if mirror.remediation_state in REMEDIATION_WORKLOAD_UNSAFE_STATES:
            self._violate(
                "workload-placement", where,
                f"scheduled onto node {node_name} under remediation "
                f"({mirror.remediation_state!r})")

    def _on_runtime_pod(self, event_type: str, pod) -> None:
        """Rollout-mode bookkeeping over the runtime DaemonSet's pods:
        per-node revision mirror, the rollback signal, and the
        no-bad-pod-after-halt assertion."""
        rollout = self.rollout
        pod_hash = pod.metadata.labels.get(
            POD_CONTROLLER_REVISION_HASH_LABEL)
        node_name = pod.spec.node_name
        if not pod_hash or not node_name:
            return
        bad = rollout.bad_revision
        if event_type == DELETED:
            if self._pod_revisions.get(node_name) == pod_hash:
                del self._pod_revisions[node_name]
            if pod_hash == bad and self.halt_evidence_at is not None \
                    and not self.rollback_signaled:
                # the machine is evacuating the condemned revision —
                # admissions after this point are re-convergence
                self.rollback_signaled = True
                self._record(f"rollback signal: bad-revision pod "
                             f"{pod.metadata.name} deleted")
            return
        self._pod_revisions[node_name] = pod_hash
        if event_type != ADDED or self.halt_evidence_at is None:
            return
        if pod_hash != bad:
            # a pod of another revision materialized after the halt:
            # only a re-pinned DaemonSet mints those
            if not self.rollback_signaled:
                self.rollback_signaled = True
                self._record(f"rollback signal: pod {pod.metadata.name} "
                             f"created on revision {pod_hash!r}")
            return
        grace_until = self.halt_evidence_at + rollout.bad_pod_grace_seconds
        if self._now() > grace_until:
            self._violate(
                "rollout-bad-pod", f"pod {pod.metadata.name}",
                f"runtime pod created on quarantined revision {bad!r} "
                f"at t={self._now():g}, past the halt grace window "
                f"(evidence at t={self.halt_evidence_at:g} + "
                f"{rollout.bad_pod_grace_seconds:g}s) — a restart "
                f"re-attempted the condemned revision")

    # -- liveness ---------------------------------------------------------
    def final_check(self) -> None:
        """End-of-run pairing/liveness assertions against live state:
        once the fleet converged, every cordon must have been paired
        with an uncordon (nothing left quarantined) and no remediation
        bookkeeping may linger."""
        self.drain()
        if self.shard is not None:
            for shard, at in sorted(self._shard_orphaned_at.items()):
                self._violate(
                    "shard-takeover", f"shard {shard}",
                    f"still orphaned at the end of the run (since "
                    f"t={at:g}) — its partition was never taken over")
        if self.window is not None:
            for name, mirror in sorted(self._nodes.items()):
                if mirror.upgrade_state in _IN_PROGRESS:
                    self._violate(
                        "window-stranded", name,
                        f"node sits mid-upgrade "
                        f"({mirror.upgrade_state!r}) at the end of the "
                        f"episode — it should have finished before the "
                        f"close t={self.window.close_seconds:g} or "
                        f"never have started")
            # abort-not-strand: a node the close overtook MID-DRAIN
            # must have been aborted back to upgrade-required (zero
            # residue, checked on the edge) or have finished — the PR 9
            # admission gate bounded the start, the abort arc bounds
            # the prediction-error stragglers
            done = str(UpgradeState.DONE)
            required = str(UpgradeState.UPGRADE_REQUIRED)
            for name in sorted(self._mid_drain_at_close or ()):
                mirror = self._nodes.get(name)
                state = mirror.upgrade_state if mirror else "gone"
                if state not in (done, required):
                    self._violate(
                        "window-stranded", name,
                        f"node was mid-drain at the window close and "
                        f"ended the episode in {state!r} — it was "
                        f"neither aborted back to upgrade-required "
                        f"nor finished")
        if self.capacity is not None \
                and self.capacity.require_modulation:
            static_eq = self.capacity.static_equivalent
            if self.capacity_effective_max is None \
                    or self.capacity_effective_max <= static_eq \
                    or self.capacity_effective_min >= static_eq:
                self._violate(
                    "capacity-modulation", "fleet",
                    f"effective budget range "
                    f"[{self.capacity_effective_min}, "
                    f"{self.capacity_effective_max}] never crossed the "
                    f"peak-safe static equivalent {static_eq} in both "
                    f"directions — the controller did not modulate")
        nodes = consume_transient(self.cluster.list_nodes)
        for node in nodes:
            name = node.metadata.name
            if self.remediation_keys is not None \
                    and self.remediation_keys.condemned_annotation \
                    in node.metadata.annotations:
                # condemned nodes are INTENTIONALLY left quarantined:
                # cordoned, parked in remediation-failed, released from
                # their slice, bookkeeping preserved for the repair crew
                continue
            if node.is_unschedulable():
                self._violate(
                    "cordon-pairing", name,
                    "node left cordoned after convergence — a cordon was "
                    "never paired with its uncordon")
            if self.remediation_keys is not None:
                prefix = (f"{self.remediation_keys.domain}/"
                          f"{self.remediation_keys.driver}-remediation")
                leftovers = sorted(
                    key for key in node.metadata.annotations
                    if key.startswith(prefix))
                if leftovers:
                    self._violate(
                        "cordon-pairing", name,
                        f"remediation bookkeeping annotations survived "
                        f"convergence: {leftovers}")

    def report(self, seed: Optional[int] = None,
               trace_tail: int = 120) -> str:
        """Human-readable violation report: the seed, every violation,
        and the trailing event trace — everything needed to replay."""
        header = (f"chaos run seed={seed}" if seed is not None
                  else "chaos run")
        lines = [f"{header}: {len(self.violations)} violation(s), "
                 f"{self.events_seen} events, {self.watch_gaps} watch "
                 f"gap(s), {self.cordons_seen} cordons / "
                 f"{self.uncordons_seen} uncordons"]
        lines += [v.describe() for v in self.violations]
        if self.violations:
            lines.append(f"--- trace (last {trace_tail} events; replay "
                         f"with run_chaos_soak(seed={seed})) ---")
            lines += self.trace[-trace_tail:]
        return "\n".join(lines)
