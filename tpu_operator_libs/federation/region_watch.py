"""RegionWatcher: watch-driven O(changed-regions) federation reads.

PR 13/14 made the federation pass correct but left its read path
O(regions·objects): every pass re-listed every region's DaemonSet,
node census, pods and ControllerRevisions even when nothing changed —
ROADMAP's named blocker for a 50-region fleet. This module replaces
the per-region poll with per-region **watch streams feeding informer
caches** (the PR 19 pump-mode :class:`~tpu_operator_libs.controller.
Informer`, reused verbatim — rewatch factories, overflow-BOOKMARK and
410-EXPIRED relist repair included), so a steady-state federation pass
performs **zero** list reads for a region whose streams delivered no
events, and exactly one targeted revision read for a region whose
DaemonSet template moved.

Three deltas from the polling path, each with its own safety story:

- **Freshness is a staleness bound on the change cursor, not a round
  of GETs.** The polled path wrote a probe annotation and verified it
  read back every pass (2 API calls x regions x passes). Here the
  probe is written only when the region's last *probe echo* — the
  probe's own MODIFIED event observed back through the watch stream —
  is older than half the configured bound. A region whose echo ages
  past the bound stops counting as fresh: admission defers and budget
  raises freeze fleet-wide, exactly the polled path's partition
  posture. The echo is a genuine write→stream round-trip, so a
  partition that cuts either direction (rejected writes, withheld
  events) makes the region stale within one bound.
- **Stream drops repair region-locally.** A dropped/410-expired stream
  relists only that region (the Informer rewatch machinery); the other
  N-1 regions keep their caches. The relist is counted — the bench
  acceptance reads these counters.
- **An own-write journal bridges the event lag.** The federation is
  the sole writer of its durable stamps (shares, bake, quarantine
  lift, pre-shift pair). A confirmed write whose MODIFIED event is
  still in flight (watch-delay faults buffer delivery) must not be
  invisible to the next pass: the ledger's raise gate sums the
  *stamped* shares, and summing a stale pre-write value would let the
  fleet jointly overdraw. Successful writes are therefore overlaid on
  the cached annotations until the cache catches up, at which point
  the journal entry retires. Delayed old events can never revert the
  overlay: the journal wins until the cache *agrees* with it.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from tpu_operator_libs.controller import Informer
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    NotFoundError,
)
from tpu_operator_libs.k8s.selectors import selector_from_labels
from tpu_operator_libs.k8s.watch import (
    KIND_DAEMON_SET,
    KIND_NODE,
    KIND_POD,
)

logger = logging.getLogger(__name__)

_TRANSIENTS = (ApiServerError, ConflictError, NotFoundError,
               TimeoutError)


class RegionWatcher:
    """List+watch cache of ONE region's federation-relevant state.

    Owns three pump-mode informers (Nodes, runtime Pods, DaemonSets)
    over the region client's ``watch()`` seam — for chaos runs that is
    the partition-gated ``_FedGateway`` stream, so partitions withhold
    events and stale-cache relists exactly like the real fault. All
    public methods are pass-paced and single-threaded (the federation
    controller drives :meth:`pump` once per pass); nothing here spawns
    threads or sleeps.
    """

    def __init__(self, name: str, client: "object", namespace: str,
                 ds_name: str, probe_key: str,
                 clock: "object",
                 staleness_seconds: float = 30.0) -> None:
        self.name = name
        self.client = client
        self.namespace = namespace
        self.ds_name = ds_name
        self._probe_key = probe_key
        self._clock = clock
        self.staleness_seconds = staleness_seconds
        # -- read accounting (the bench acceptance's evidence) --
        #: list API round-trips issued (initial syncs, relists,
        #: targeted revision reads).
        self.api_reads = 0
        #: objects those lists returned.
        self.read_objects = 0
        #: relists after the initial sync (overflow, 410, stream drop).
        self.relists = 0
        #: probe annotations written (the staleness-bound cadence).
        self.probe_writes = 0
        # -- change cursor / freshness --
        #: bumped once per ingested watch event; the controller's
        #: "did anything change since my last pass" signal.
        self.cursor = 0
        self._fresh_at: Optional[float] = None
        self._pending_probe: Optional[str] = None
        #: own confirmed DS-annotation writes the cache has not
        #: reflected yet (key -> value-or-None); see module docstring.
        self._journal: "dict[str, Optional[str]]" = {}
        # -- revision oracle --
        self._newest = ""
        #: set on any DS template-generation move (and at start):
        #: the next view issues ONE list_controller_revisions read.
        self._revision_dirty = True
        self._informers: "dict[str, Informer]" = {}
        self._synced_kinds: "set[str]" = set()
        self._started = False

    # ------------------------------------------------------------------
    # informer plumbing
    # ------------------------------------------------------------------
    def _counted_lister(self, kind: str,
                        lister: Callable[[], list]) -> Callable[[], list]:
        def counted() -> list:
            self.api_reads += 1
            if kind in self._synced_kinds:
                self.relists += 1
            out = list(lister())
            self.read_objects += len(out)
            self._synced_kinds.add(kind)
            return out
        return counted

    def _build_informers(self) -> None:
        client = self.client
        ns = self.namespace
        specs = (
            (KIND_NODE, lambda: client.list_nodes()),
            (KIND_POD, lambda: client.list_pods(namespace=ns)),
            (KIND_DAEMON_SET, lambda: client.list_daemon_sets(ns)),
        )
        for kind, lister in specs:
            def rewatch(kind=kind) -> "object":
                return client.watch(kinds={kind}, namespace=ns)
            informer = Informer(
                self._counted_lister(kind, lister), rewatch(),
                name=f"fed-{self.name}-{kind.lower()}",
                threaded=False, rewatch=rewatch)
            informer.add_event_handler(
                on_add=lambda obj, kind=kind: self._ingest(kind, None,
                                                           obj),
                on_update=lambda old, new, kind=kind:
                self._ingest(kind, old, new),
                on_delete=lambda obj, kind=kind: self._ingest(kind, obj,
                                                              None))
            self._informers[kind] = informer

    def _ingest(self, kind: str, old: "object", new: "object") -> None:
        """Event-handler tap: every ingested event moves the region's
        change cursor; DaemonSet events additionally resolve probe
        echoes, retire caught-up journal entries, and dirty the
        revision oracle when the template generation moved."""
        self.cursor += 1
        if kind != KIND_DAEMON_SET or new is None:
            return
        meta = getattr(new, "metadata", None)
        if meta is None or meta.name != self.ds_name:
            return
        annotations = meta.annotations
        if self._pending_probe is not None and annotations.get(
                self._probe_key) == self._pending_probe:
            # the probe's own event came back around: a full
            # write->stream round-trip at this instant
            self._fresh_at = self._clock.now()
            self._pending_probe = None
        for key, value in list(self._journal.items()):
            present = annotations.get(key)
            if present == value or (value is None
                                    and key not in annotations):
                del self._journal[key]
        if old is not None:
            old_gen = getattr(getattr(old, "spec", None),
                              "template_generation", None)
            new_gen = getattr(getattr(new, "spec", None),
                              "template_generation", None)
            if old_gen != new_gen:
                self._revision_dirty = True
        else:
            self._revision_dirty = True

    # ------------------------------------------------------------------
    # pass-paced drive
    # ------------------------------------------------------------------
    def pump(self) -> bool:
        """Start (once) and pump every informer; returns False when a
        transient kept any cache from syncing/repairing this pass (the
        region reads as unreachable; next pass retries)."""
        if not self._informers:
            self._build_informers()
        ok = True
        for informer in self._informers.values():
            try:
                informer.start()
                informer.pump()
            except _TRANSIENTS:
                ok = False
        return ok

    def maybe_probe(self, now: float) -> None:
        """Write the freshness probe when the last echo is older than
        half the staleness bound (or never observed), then pump the
        DaemonSet stream once more so an un-delayed echo lands in the
        SAME pass — the polled path's write+read-back equivalence,
        carried by the stream instead of a GET."""
        if self._fresh_at is not None \
                and now - self._fresh_at < self.staleness_seconds / 2.0:
            return
        value = f"{now:g}"
        try:
            self.client.patch_daemon_set_annotations(
                self.namespace, self.ds_name, {self._probe_key: value})
        except _TRANSIENTS:
            return  # no echo will come; the bound does the rest
        self.probe_writes += 1
        self._pending_probe = value
        ds_informer = self._informers.get(KIND_DAEMON_SET)
        if ds_informer is not None:
            try:
                ds_informer.pump()
            except _TRANSIENTS:
                pass  # echo arrives on a later pump or never (stale)
        # a probe that did not echo leaves _pending_probe set; a
        # replacement probe simply supersedes it (last write wins on
        # the annotation, so only the newest value can echo)

    def is_fresh(self, now: float) -> bool:
        return (self._fresh_at is not None
                and now - self._fresh_at <= self.staleness_seconds)

    # ------------------------------------------------------------------
    # cached reads (zero API traffic)
    # ------------------------------------------------------------------
    def cached_daemon_set(self) -> "Optional[object]":
        informer = self._informers.get(KIND_DAEMON_SET)
        if informer is None:
            return None
        return informer.get(self.namespace, self.ds_name)

    def cached_nodes(self) -> list:
        informer = self._informers.get(KIND_NODE)
        return informer.list() if informer is not None else []

    def cached_pods(self) -> list:
        informer = self._informers.get(KIND_POD)
        return informer.list() if informer is not None else []

    def annotations(self) -> "dict[str, str]":
        """The runtime DS annotations as this pass should trust them:
        the informer cache overlaid with the own-write journal (a
        confirmed write beats a cache the stream has not caught up)."""
        ds = self.cached_daemon_set()
        merged = dict(ds.metadata.annotations) if ds is not None else {}
        for key, value in self._journal.items():
            if value is None:
                merged.pop(key, None)
            else:
                merged[key] = value
        return merged

    def newest_revision(self) -> str:
        """The region DS's newest ControllerRevision hash — read from
        the apiserver ONLY when a DS event moved the template
        generation since the last read (the one O(changed) read of a
        changed region's pass)."""
        ds = self.cached_daemon_set()
        if ds is None:
            return ""
        if not self._revision_dirty:
            return self._newest
        try:
            selector = selector_from_labels(ds.spec.selector)
            self.api_reads += 1
            revisions = self.client.list_controller_revisions(
                self.namespace, selector)
            self.read_objects += len(revisions)
        except _TRANSIENTS:
            return self._newest  # keep the last oracle; retry next pass
        prefix = f"{ds.metadata.name}-"
        owned = [r for r in revisions
                 if r.metadata.name.startswith(prefix)
                 and "-" not in r.metadata.name[len(prefix):]]
        if owned:
            newest = max(owned, key=lambda r: r.revision)
            self._newest = newest.metadata.name[len(prefix):]
        else:
            self._newest = ""
        self._revision_dirty = False
        return self._newest

    # ------------------------------------------------------------------
    # journaled writes
    # ------------------------------------------------------------------
    def patch_annotations(
            self, annotations: "dict[str, Optional[str]]") -> None:
        """Write-through DS annotation patch: on success every entry is
        journaled so the very next pass sees the stamped truth even if
        the MODIFIED event is delayed. Transients propagate (callers
        keep the polled path's defer-and-retry semantics)."""
        self.client.patch_daemon_set_annotations(
            self.namespace, self.ds_name, annotations)
        for key, value in annotations.items():
            if key != self._probe_key:
                self._journal[key] = value

    def note_rolled(self, revision: str) -> None:
        """A successful admission roll makes ``revision`` the newest
        ControllerRevision synchronously; record it so a delayed DS
        event cannot make the next pass re-admit the region. The event,
        when it lands, re-dirties the oracle and re-verifies."""
        self._newest = revision

    def read_accounting(self) -> "dict[str, int]":
        expired = sum(i.expired_relists
                      for i in self._informers.values())
        return {"apiReads": self.api_reads,
                "readObjects": self.read_objects,
                "relists": self.relists,
                "expiredRelists": expired,
                "probeWrites": self.probe_writes}
