"""FederationController: region-as-canary global rollouts.

One controller, many clusters. Each region runs the SAME per-cluster
operator this library already ships — sharded, traffic-aware, with its
own RolloutGuard — and the federation layer drives it purely through
the CRD/policy surface it already consumes:

- **admission** is rolling the region's runtime DaemonSet to the
  target revision (the region operator notices outdated pods and walks
  its own waves);
- **budget** is the durable per-region share stamp
  (:class:`~tpu_operator_libs.federation.ledger.
  FederationBudgetLedger`) the region operator reads as its effective
  ``maxUnavailable`` — the global B is enforced region-locally, so a
  partitioned or freshly-restarted regional controller cannot
  overdraw;
- **verdicts** are the region guard's own quarantine annotation: the
  canary region's guard halts and rolls back LOCALLY on a bad
  revision, and the federation lifts the verdict fleet-wide by
  stamping every other region's DaemonSet in the same pass.

Everything durable lives on the regions' DaemonSets (share stamps, the
canary bake stamp, quarantine records, the freshness probe); the
controller object carries only counters and advisory bookkeeping, so a
federation-controller crash-restart resumes the rollout mid-wave from
the regions' state alone — the ``federation-resume`` invariant the
chaos gate pins.

Partition model: before trusting a region's reads, the controller
writes a probe annotation and verifies it reads back. A region whose
probe fails is *partitioned*: its stale data is used for display only,
it is never admitted, and — because a stale read could hide a share
stamp a previous incarnation granted — no region's share anywhere may
be RAISED until the whole fleet reads fresh again (decreases stay
allowed; they only tighten the global inequality).

Read path: with ``watch=True`` the per-region poll is replaced by
per-region watch streams feeding informer caches
(:class:`~tpu_operator_libs.federation.region_watch.RegionWatcher`):
a steady-state pass reads only regions whose streams delivered
events, stream drops fall back to a targeted relist of that region
only, and the freshness probe becomes a staleness bound on the
region's change cursor. Both modes feed the same per-pass read
accounting (``fed_api_reads`` / ``fed_read_objects`` /
``fed_relists`` and the status ``reads`` block — the
``read_accounting()`` idiom of k8s/cached.py), which is how the
50-region bench proves the O(changed-regions) claim.

Session pre-shift: before admitting a region, the controller reserves
session capacity in an adjacent region via a durable region-level
reservation→ready stamp pair on the reserve region's DaemonSet (the
PrewarmCoordinator idiom of upgrade/handover.py lifted to region
granularity — reserve crash-ordered before ready, both released in
ONE patch, zero residue), requires readiness, then admits — so a
region admission drops zero interactive sessions globally.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Optional

from tpu_operator_libs.api.federation_policy import FederationPolicySpec
from tpu_operator_libs.api.upgrade_policy import (
    scaled_value_from_int_or_percent,
)
from tpu_operator_libs.consts import (
    POD_CONTROLLER_REVISION_HASH_LABEL,
    FederationKeys,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.federation.ledger import FederationBudgetLedger
from tpu_operator_libs.federation.region_watch import RegionWatcher
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    NotFoundError,
)
from tpu_operator_libs.k8s.selectors import selector_from_labels
from tpu_operator_libs.obs.audit import DecisionAudit
from tpu_operator_libs.util import Clock

logger = logging.getLogger(__name__)

#: Transients a federation pass rides out per region (the region is
#: simply skipped this pass and re-probed next pass).
#: Per-node duration assumed by the region-admission preflight when
#: sizing a region's rollout horizon — the duration predictor's cold
#: prior (upgrade/predictor.py ``prior_seconds``); the federation layer
#: has no per-node model, so the forecast uses the same documented
#: cold-start estimate the node-level planner falls back to.
REGION_NODE_PRIOR_SECONDS = 120.0

_TRANSIENTS = (ApiServerError, ConflictError, NotFoundError,
               TimeoutError)


@dataclass
class RegionHandle:
    """One region's access surface.

    ``client`` is the region apiserver's K8sClient (possibly behind a
    partition-detecting proxy); ``utilization`` is the region's live
    serving-load signal in [0, 1] (the PR 10 capacity picture, one
    number per region — a regional capacity controller's utilization,
    a gateway QPS ratio...), consulted for follow-the-sun ordering.
    ``roll`` overrides how an admission rolls the region's DaemonSet
    to a revision (default: the client's ``bump_daemon_set_revision``,
    which the FakeCluster regions of the chaos sim implement; a real
    deployment patches the DS pod template).
    """

    name: str
    client: object
    namespace: str = "tpu-system"
    ds_name: str = "libtpu"
    utilization: Optional[Callable[[float], float]] = None
    #: Preferred over the scalar ``utilization`` trace when present:
    #: a callable returning the region's REAL per-region
    #: ``CapacityBudgetController.last_status`` block (PR 10;
    #: ``cluster_status["capacity"]`` shape — utilization, demand,
    #: headroom, effective/static budget, paused). None (or a call
    #: returning None — e.g. the controller has not evaluated yet)
    #: falls back to the scalar signal, so regions upgrade to the
    #: richer feed one at a time.
    capacity_status: Optional[Callable[[], Optional[dict]]] = None
    roll: Optional[Callable[[str], None]] = None
    #: Live interactive-session count hosted by this region (the
    #: pre-shift reservation's ``slots`` sizing). None falls back to
    #: the region's node census — a conservative proxy.
    sessions: Optional[Callable[[], int]] = None
    #: Readiness probe for this region AS A RESERVE: called with
    #: ``(slots, reserved_at_epoch)``, True once the reserved session
    #: capacity is actually serving-ready. None = ready immediately
    #: (the PrewarmCoordinator "broken hook must not wedge" posture is
    #: inverted here on purpose: a region with no warmup signal has
    #: nothing to warm).
    preshift_ready: Optional[Callable[[int, float], bool]] = None

    def roll_to(self, revision: str) -> None:
        if self.roll is not None:
            self.roll(revision)
            return
        self.client.bump_daemon_set_revision(self.namespace,
                                             self.ds_name, revision)


@dataclass
class RegionView:
    """One pass's (possibly stale) picture of a region."""

    name: str
    #: True only when the freshness probe landed AND read back — the
    #: precondition for trusting anything below for decisions.
    reachable: bool = False
    ds_found: bool = False
    newest: str = ""
    total: int = 0
    nodes_done: int = 0
    unavailable: int = 0
    ready_on_target: int = 0
    share: Optional[int] = None
    quarantined: frozenset = frozenset()
    bake_stamp: str = ""
    utilization: Optional[float] = None
    #: The region's live capacity picture when its handle exposes the
    #: real controller status block (None = scalar-signal region).
    capacity: Optional[dict] = None
    #: Raw pre-shift stamps found on THIS region's DS (this region is
    #: the RESERVE of the pair's source region): reservation
    #: ``<source>:<revision>:<slots>:<epoch>``, ready
    #: ``<source>:<revision>:<epoch>``; "" when absent.
    preshift_reservation: str = ""
    preshift_ready: str = ""

    def done_on(self, revision: str) -> bool:
        """Region fully converged on ``revision``: DS points at it,
        every node upgrade-done and schedulable, every runtime pod on
        the hash and Ready."""
        return (self.ds_found and self.newest == revision
                and self.total > 0
                and self.nodes_done == self.total
                and self.ready_on_target == self.total
                and self.unavailable == 0)


class FederationController:
    """The multi-cluster rollout brain. Drive with
    :meth:`reconcile(target_revision)` once per federation pass."""

    def __init__(self, regions: "list[RegionHandle]",
                 policy: Optional[FederationPolicySpec] = None,
                 keys: Optional[FederationKeys] = None,
                 upgrade_keys: Optional[UpgradeKeys] = None,
                 clock: Optional[Clock] = None,
                 audit: Optional[DecisionAudit] = None,
                 watch: bool = False) -> None:
        if not regions:
            raise ValueError("at least one region is required")
        names = [handle.name for handle in regions]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate region names: {sorted(names)}")
        self.regions: "dict[str, RegionHandle]" = {
            handle.name: handle for handle in regions}
        self.policy = policy or FederationPolicySpec()
        self.keys = keys or FederationKeys()
        self.upgrade_keys = upgrade_keys or UpgradeKeys()
        self._clock = clock or Clock()
        self.ledger = FederationBudgetLedger(self.keys)
        #: Region-level decision audit (obs/ idiom; ``node`` carries
        #: the region name). Feeds explain_region and the chaos
        #: monitor's cross-incarnation mirror.
        self.audit = audit or DecisionAudit(max_records=2048,
                                            clock=self._clock)
        # -- advisory in-memory state (a restart loses none of the
        # safety story, only wait bookkeeping and cached sizes) --
        #: region -> last known managed-node count (used for the
        #: global-budget denominator while a region is partitioned —
        #: an unknown region contributes its last census, or 0 on a
        #: fresh restart, which only SHRINKS B: the conservative side).
        self._region_totals: "dict[str, int]" = {}
        #: region -> virtual time it started waiting for its trough.
        self._trough_wait_started: "dict[str, float]" = {}
        #: region -> virtual time it started waiting for a pre-shift
        #: reserve (liveness bookkeeping, same restart trade as the
        #: trough wait).
        self._preshift_wait_started: "dict[str, float]" = {}
        self._last_views: "dict[str, RegionView]" = {}
        self._last_target = ""
        # -- watch mode (O(changed-regions) reads) --
        self.watch = watch
        self._watchers: "dict[str, RegionWatcher]" = {}
        if watch:
            for handle in regions:
                self._watchers[handle.name] = RegionWatcher(
                    handle.name, handle.client, handle.namespace,
                    handle.ds_name, self.keys.probe_annotation,
                    self._clock,
                    staleness_seconds=self.policy
                    .watch_staleness_seconds)
        #: region -> change cursor at the end of the last pass (the
        #: per-pass ``regionsChanged`` evidence).
        self._last_cursors: "dict[str, int]" = {}
        # -- lifetime counters (metrics.observe_federation feed) --
        self.admissions_total = 0
        self.quarantine_stamps_total = 0
        self.bake_stamps_total = 0
        self.raise_freeze_passes_total = 0
        self.share_stamps_total = 0
        self.partitioned_reads_total = 0
        self.passes_total = 0
        self.last_status: "Optional[dict]" = None
        #: region -> most recent admission-preflight forecast (empty
        #: while the policy has no preflight) — the status /
        #: explain_region feed and the admission gate's evidence.
        self.last_preflight: "dict[str, dict]" = {}
        #: lifetime region admissions deferred by a required-mode
        #: preflight breach (metrics/chaos teeth).
        self.preflight_rejections_total = 0
        # -- read accounting (k8s/cached.py read_accounting() idiom,
        # lifted to the federation pass; poll mode counts its lists,
        # watch mode aggregates the RegionWatchers) --
        self.fed_api_reads = 0
        self.fed_read_objects = 0
        self.fed_relists = 0
        self.fed_probe_writes = 0
        self._last_reads_block: "dict" = {}
        # -- session pre-shift lifetime counters --
        self.preshift_reservations_total = 0
        self.preshift_ready_total = 0
        self.preshift_released_total = 0
        self.preshift_holds_total = 0
        self.preshift_expired_waits_total = 0

    # ------------------------------------------------------------------
    # region reads
    # ------------------------------------------------------------------
    def _read_region(self, handle: RegionHandle, now: float,
                     target: str) -> RegionView:
        if self.watch:
            return self._read_region_watch(handle, now, target)
        view = RegionView(name=handle.name)
        client = handle.client
        probe_value = f"{now:g}"
        probed = False
        try:
            client.patch_daemon_set_annotations(
                handle.namespace, handle.ds_name,
                {self.keys.probe_annotation: probe_value})
            probed = True
            self.fed_probe_writes += 1
        except _TRANSIENTS:
            self.partitioned_reads_total += 1
        try:
            self.fed_api_reads += 1
            daemon_sets = client.list_daemon_sets(handle.namespace)
            self.fed_read_objects += len(daemon_sets)
            ds = next((d for d in daemon_sets
                       if d.metadata.name == handle.ds_name), None)
            if ds is not None:
                view.ds_found = True
                annotations = ds.metadata.annotations
                # freshness: the probe we just wrote must read back —
                # a stale cache serving pre-partition snapshots fails
                # here even when the write "succeeded" before the cut
                view.reachable = probed and annotations.get(
                    self.keys.probe_annotation) == probe_value
                self._fill_view_annotations(view, annotations)
                view.newest = self._newest_revision(client, handle, ds)
            self.fed_api_reads += 1
            nodes = client.list_nodes()
            self.fed_read_objects += len(nodes)
            self._fill_view_nodes(view, nodes)
            self.fed_api_reads += 1
            pods = client.list_pods(namespace=handle.namespace)
            self.fed_read_objects += len(pods)
            view.ready_on_target = self._ready_on_target(pods, target)
        except _TRANSIENTS:
            view.reachable = False
        if view.reachable:
            self._region_totals[handle.name] = view.total
        return view

    def _fill_view_annotations(self, view: RegionView,
                               annotations: "dict") -> None:
        view.share = self.ledger.share_from(annotations)
        quarantined = annotations.get(
            self.upgrade_keys.quarantined_revision_annotation)
        if quarantined:
            view.quarantined = frozenset({quarantined})
        view.bake_stamp = annotations.get(
            self.keys.bake_passed_annotation, "")
        view.preshift_reservation = annotations.get(
            self.keys.preshift_reservation_annotation, "")
        view.preshift_ready = annotations.get(
            self.keys.preshift_ready_annotation, "")

    def _fill_view_nodes(self, view: RegionView, nodes: list) -> None:
        view.total = len(nodes)
        state_label = self.upgrade_keys.state_label
        done = str(UpgradeState.DONE)
        for node in nodes:
            if node.metadata.labels.get(state_label) == done:
                view.nodes_done += 1
            if node.is_unschedulable() or not node.is_ready():
                view.unavailable += 1

    @staticmethod
    def _ready_on_target(pods: list, target: str) -> int:
        return sum(
            1 for pod in pods
            if pod.controller_owner() is not None
            and pod.metadata.labels.get(
                POD_CONTROLLER_REVISION_HASH_LABEL) == target
            and pod.is_ready())

    def _read_region_watch(self, handle: RegionHandle, now: float,
                           target: str) -> RegionView:
        """The O(changed-regions) read: pump the region's streams,
        re-probe only when the staleness bound asks, and build the
        view entirely from informer caches (journal-overlaid). A
        steady-state unchanged region costs ZERO list reads here."""
        watcher = self._watchers[handle.name]
        view = RegionView(name=handle.name)
        pumped = watcher.pump()
        if not pumped:
            self.partitioned_reads_total += 1
        watcher.maybe_probe(now)
        ds = watcher.cached_daemon_set()
        if ds is not None:
            view.ds_found = True
            self._fill_view_annotations(view, watcher.annotations())
            view.newest = watcher.newest_revision()
        # freshness: the probe's own event observed back through the
        # stream, within the staleness bound — the cursor-freshness
        # contract replacing the per-pass write+read-back round trip
        view.reachable = (pumped and view.ds_found
                          and watcher.is_fresh(now))
        self._fill_view_nodes(view, watcher.cached_nodes())
        view.ready_on_target = self._ready_on_target(
            watcher.cached_pods(), target)
        if view.reachable:
            self._region_totals[handle.name] = view.total
        return view

    def _newest_revision(self, client: "object", handle: RegionHandle,
                         ds: "object") -> str:
        """Newest ControllerRevision hash of the region's runtime DS
        (the pod-manager oracle, minus the per-snapshot memo — the
        federation reads each region once per pass)."""
        try:
            selector = selector_from_labels(ds.spec.selector)
            revisions = client.list_controller_revisions(
                handle.namespace, selector)
        except _TRANSIENTS:
            return ""
        prefix = f"{ds.metadata.name}-"
        owned = [r for r in revisions
                 if r.metadata.name.startswith(prefix)
                 and "-" not in r.metadata.name[len(prefix):]]
        if not owned:
            return ""
        newest = max(owned, key=lambda r: r.revision)
        return newest.metadata.name[len(prefix):]

    # ------------------------------------------------------------------
    # the federation pass
    # ------------------------------------------------------------------
    def reconcile(self, target_revision: str) -> dict:
        """One federation pass toward ``target_revision``. Reads every
        region (probe-verified), lifts quarantine verdicts fleet-wide,
        stamps the canary bake, admits regions (canary first, then
        follow-the-sun waves), and maintains the per-region budget
        shares. Returns the pass's status block."""
        now = self._clock.now()
        self.passes_total += 1
        self.audit.begin_pass()
        reads_before = (self.fed_api_reads, self.fed_read_objects,
                        self.fed_relists, self.fed_probe_writes)
        policy = self.policy
        if not policy.enable or not target_revision:
            self.last_status = {"target": target_revision,
                                "enabled": policy.enable,
                                "regions": {}}
            return self.last_status
        fleet = sorted(self.regions)
        views = {name: self._read_region(self.regions[name], now,
                                         target_revision)
                 for name in fleet}
        for name in fleet:
            view = views[name]
            if view.utilization is None:
                # the REAL per-region capacity-controller status block
                # wins over the scalar utilization trace: it is the
                # same number the region's own admission decisions ran
                # on this pass, plus the demand/headroom/paused context
                # surfaced in the region status below
                status_source = self.regions[name].capacity_status
                if status_source is not None:
                    try:
                        status = status_source()
                    except Exception:  # noqa: BLE001 — a broken
                        status = None  # signal must not wedge a pass
                    if status is not None:
                        view.capacity = {
                            key: status.get(key)
                            for key in ("utilization", "demand",
                                        "headroom",
                                        "capacityAvailable",
                                        "effectiveBudget",
                                        "staticBudget", "paused")}
                        utilization = status.get("utilization")
                        if utilization is not None:
                            view.utilization = max(
                                0.0, min(1.0, float(utilization)))
            if view.utilization is None:
                signal = self.regions[name].utilization
                if signal is not None:
                    try:
                        view.utilization = max(0.0, min(1.0,
                                                        signal(now)))
                    except Exception:  # noqa: BLE001 — a broken signal
                        view.utilization = None  # must not wedge a pass
        self._last_views = views
        self._last_target = target_revision
        # per-pass read accounting: watch mode aggregates the lifetime
        # RegionWatcher counters (poll mode incremented inline above);
        # regionsChanged compares each region's change cursor against
        # the last pass — the O(changed-regions) evidence the bench
        # and the soak read
        if self.watch:
            watchers = list(self._watchers.values())
            self.fed_api_reads = sum(w.api_reads for w in watchers)
            self.fed_read_objects = sum(w.read_objects
                                        for w in watchers)
            self.fed_relists = sum(w.relists for w in watchers)
            self.fed_probe_writes = sum(w.probe_writes
                                        for w in watchers)
            changed = sum(
                1 for name in fleet
                if self._watchers[name].cursor
                != self._last_cursors.get(name, 0))
            self._last_cursors = {name: self._watchers[name].cursor
                                  for name in fleet}
        else:
            changed = len(fleet)
        self._last_reads_block = {
            "mode": "watch" if self.watch else "poll",
            "apiReads": self.fed_api_reads - reads_before[0],
            "readObjects": self.fed_read_objects - reads_before[1],
            "relists": self.fed_relists - reads_before[2],
            "probeWrites": self.fed_probe_writes - reads_before[3],
            "regionsChanged": changed,
            "totalRegions": len(fleet),
        }
        # region-admission preflight: forecast every region's rollout
        # against its live traffic signal BEFORE any admission (and
        # before any budget share is stamped); _admit consults the
        # verdicts below
        self.last_preflight = {}
        if policy.preflight is not None and policy.preflight.enabled:
            for name in fleet:
                forecast = self._forecast_region(views[name], now)
                if forecast is not None:
                    self.last_preflight[name] = forecast
        canary = self._canary_region(views)

        quarantined: set[str] = set()
        for view in views.values():
            quarantined |= view.quarantined
        halted = target_revision in quarantined
        if halted:
            self._propagate_quarantine(views, target_revision)

        baked, bake_at = self._bake_state(views, canary,
                                          target_revision)
        # pre-shift release sweep runs even when halted (and even if
        # the policy knob was just switched off): a rollback that
        # quiesced must still free its reserve, and residue from a
        # previous incarnation must never outlive its source's arc
        self._preshift_sweep(views, target_revision, now, halted)
        admitted: list[str] = []
        if not halted:
            admitted = self._admit(views, canary, target_revision,
                                   baked, now)

        shares = self._maintain_shares(views, canary, target_revision,
                                       admitted)

        status = {
            "target": target_revision,
            "canaryRegion": canary,
            "halted": halted,
            "quarantined": sorted(quarantined),
            "baked": baked,
            "bakePassedAt": bake_at,
            "globalBudget": self._global_budget(views),
            "shares": shares,
            "admittedThisPass": admitted,
            "reads": dict(self._last_reads_block),
            "preshift": {
                "enabled": policy.session_pre_shift,
                "reservations": {
                    name: view.preshift_reservation
                    for name, view in sorted(views.items())
                    if view.preshift_reservation},
                "ready": {
                    name: view.preshift_ready
                    for name, view in sorted(views.items())
                    if view.preshift_ready},
                "waiting": sorted(self._preshift_wait_started),
            },
            "regions": {
                name: {
                    "reachable": view.reachable,
                    "revision": view.newest,
                    "total": view.total,
                    "done": view.done_on(target_revision),
                    "unavailable": view.unavailable,
                    "share": view.share,
                    "utilization": view.utilization,
                    "capacity": view.capacity,
                    "preflight": self.last_preflight.get(name),
                    "phase": self._phase(view, canary,
                                         target_revision, halted,
                                         baked),
                } for name, view in sorted(views.items())},
        }
        self.last_status = status
        return status

    def _phase(self, view: RegionView, canary: str, target: str,
               halted: bool, baked: bool) -> str:
        if not view.reachable:
            return "partitioned"
        if halted:
            return "quarantined" if view.newest == target \
                or view.quarantined else "held"
        if view.done_on(target):
            return "done"
        if view.newest == target:
            return "canary-baking" if view.name == canary \
                and not baked else "upgrading"
        return "pending"

    def _canary_region(self, views: "dict[str, RegionView]") -> str:
        """The configured canary region, or — with ``canaryRegion``
        unset — the lowest-utilization region (unknown utilization
        sorts last; ties by name). Evaluated against live signals, so
        a restarted controller lands on the same region as long as the
        traffic picture has not inverted mid-wave; pin ``canaryRegion``
        for a byte-stable choice."""
        if self.policy.canary_region:
            return self.policy.canary_region
        return self._wave_order(views, views)[0]

    @staticmethod
    def _wave_order(views: "dict[str, RegionView]",
                    names: "object") -> "list[str]":
        """Deterministic follow-the-sun order: utilization ascending,
        unknown-signal regions last, ties broken by region name. The
        utilization is ROUNDED before comparison — live float signals
        jitter in the low decimals across controller incarnations, and
        an unrounded 1e-12 difference silently reorders what should be
        a name-broken tie, making wave order (and the elected canary)
        incarnation-dependent. Shared by admission, the canary
        election, and the pre-shift reserve pick."""
        def rank(name: str) -> tuple:
            u = views[name].utilization
            return (round(u, 6) if u is not None else 2.0, name)
        return sorted(names, key=rank)

    # ------------------------------------------------------------------
    # quarantine lift (canary containment's second half)
    # ------------------------------------------------------------------
    def _propagate_quarantine(self, views: "dict[str, RegionView]",
                              target: str) -> None:
        """A region guard condemned ``target``: stamp every other
        reachable region's DaemonSet in the SAME pass, so recovered or
        partition-healed regional controllers re-derive the fleet halt
        from their own cluster state before admitting anything."""
        key = self.upgrade_keys.quarantined_revision_annotation
        for name in sorted(views):
            view = views[name]
            if not view.reachable or target in view.quarantined:
                continue
            try:
                self._patch_region(name, {key: target})
            except _TRANSIENTS as exc:
                logger.warning("quarantine stamp for region %s "
                               "deferred: %s", name, exc)
                continue
            view.quarantined = view.quarantined | {target}
            self.quarantine_stamps_total += 1
            self.audit.record(
                "fed-quarantine", name,
                decision=f"quarantine {target}",
                rule="canary-verdict-lifted",
                inputs={"revision": target})
            logger.warning(
                "FEDERATION HALT: revision %s quarantined fleet-wide "
                "(stamped region %s)", target, name)

    # ------------------------------------------------------------------
    # canary bake
    # ------------------------------------------------------------------
    def _bake_state(self, views: "dict[str, RegionView]", canary: str,
                    target: str) -> "tuple[bool, Optional[float]]":
        """(baked, stamped_at): reads the durable bake stamp off the
        canary region's DaemonSet — writing it first when the canary
        region just converged on the target. Only a FRESH canary read
        counts: a stale view could hide a quarantine racing the bake."""
        view = views.get(canary)
        if view is None or not view.reachable:
            return False, None
        revision, _, passed_at = view.bake_stamp.partition(":")
        if revision == target and passed_at:
            try:
                stamped = float(passed_at)
                now = self._clock.now()
                return now >= stamped + self.policy.bake_seconds, stamped
            except ValueError:
                pass  # corrupt stamp: fall through and re-derive
        if not view.done_on(target) or target in view.quarantined:
            return False, None
        now = self._clock.now()
        try:
            self._patch_region(canary, {
                self.keys.bake_passed_annotation: f"{target}:{now:g}"})
        except _TRANSIENTS as exc:
            logger.warning("bake stamp for %s deferred: %s", target, exc)
            return False, None
        self.bake_stamps_total += 1
        self.audit.record(
            "fed-bake", canary, decision=f"bake started for {target}",
            rule="canary-region-converged",
            inputs={"bakeSeconds": self.policy.bake_seconds})
        logger.info("canary region %s converged on %s; baking %ds "
                    "before fleet waves", canary, target,
                    self.policy.bake_seconds)
        return self.policy.bake_seconds <= 0, now

    # ------------------------------------------------------------------
    # region-admission preflight (upgrade/preflight.py at region grain)
    # ------------------------------------------------------------------
    def _forecast_region(self, view: RegionView,
                         now: float) -> "Optional[dict]":
        """What-if forecast for admitting this region now, from reads
        the pass already made (no extra cluster traffic — the
        federation-side read-only guarantee is structural).

        Horizon: the whole region rolled one budget-share-wide wave at
        a time at the predictor's documented per-node prior. Risk: the
        peak of the region's live utilization signal across that
        horizon against the serving capacity left while a share of the
        fleet is held out — the same shortfall fraction the node-level
        replay computes."""
        spec = self.policy.preflight
        if spec is None or not spec.enabled:
            return None
        name = view.name
        total = view.total if view.reachable \
            else self._region_totals.get(name, 0)
        if total <= 0:
            return None
        share = view.share or max(1, scaled_value_from_int_or_percent(
            self.policy.global_max_unavailable, total, round_up=True))
        share = min(share, total)
        waves = -(-total // share)
        horizon = REGION_NODE_PRIOR_SECONDS * waves
        avail = 1.0 - share / total
        handle = self.regions[name]
        peak = view.utilization if view.utilization is not None else 0.0
        signal = handle.utilization
        if signal is not None:
            step = horizon / 16
            for i in range(17):
                try:
                    peak = max(peak, min(1.0, max(
                        0.0, float(signal(now + i * step)))))
                except Exception:  # noqa: BLE001 — a broken signal
                    break  # must not wedge the pass
        risk = round(max(0.0, peak - avail) / peak, 4) if peak > 0 \
            else 0.0
        breaches: list[str] = []
        if spec.max_forecast_makespan_seconds > 0 \
                and horizon > spec.max_forecast_makespan_seconds:
            breaches.append("makespan")
        if risk > spec.max_forecast_slo_risk_fraction:
            breaches.append("slo-risk")
        if not breaches:
            verdict = "admit"
        elif spec.mode == "required":
            verdict = "reject"
        else:
            verdict = "advisory-breach"
        return {
            "mode": spec.mode,
            "generatedAtSeconds": round(now, 1),
            "horizonSeconds": round(horizon, 1),
            "waves": waves,
            "shareAssumed": share,
            "peakUtilization": round(peak, 4),
            "sloRiskFraction": risk,
            "thresholds": {
                "maxForecastSloRiskFraction":
                    spec.max_forecast_slo_risk_fraction,
                "maxForecastMakespanSeconds":
                    spec.max_forecast_makespan_seconds,
            },
            "breaches": breaches,
            "verdict": verdict,
        }

    def _preflight_defers(self, region: str) -> bool:
        """True when a required-mode forecast breach defers this
        region's admission this pass (audited; the region stays out of
        ``admitted`` so :meth:`_maintain_shares` stamps it no share)."""
        forecast = self.last_preflight.get(region)
        if forecast is None or forecast["verdict"] != "reject":
            return False
        self.preflight_rejections_total += 1
        self.audit.record_hold(
            region, rule="preflight-rejected",
            inputs={"breaches": ",".join(forecast["breaches"]),
                    "sloRiskFraction": forecast["sloRiskFraction"],
                    "horizonSeconds": forecast["horizonSeconds"]})
        logger.info(
            "federation preflight deferred region %s: %s (risk %.3f "
            "over %.0fs horizon)", region,
            ",".join(forecast["breaches"]),
            forecast["sloRiskFraction"], forecast["horizonSeconds"])
        return True

    # ------------------------------------------------------------------
    # admissions (canary first, then follow-the-sun waves)
    # ------------------------------------------------------------------
    def _admit(self, views: "dict[str, RegionView]", canary: str,
               target: str, baked: bool, now: float) -> "list[str]":
        admitted: list[str] = []
        canary_view = views.get(canary)
        if canary_view is not None and canary_view.reachable \
                and canary_view.ds_found \
                and canary_view.newest != target \
                and target not in canary_view.quarantined \
                and not self._preflight_defers(canary) \
                and not self._holder_defers(views, canary) \
                and self._preshift_gate(views, canary, target, now):
            if self._roll(canary, target, rule="canary-region"):
                admitted.append(canary)
                # mark the roll in this pass's views so later gate
                # calls see the region as mid-upgrade (never picked
                # as a reserve in the same pass it was admitted)
                canary_view.newest = target
        if not baked:
            for name in sorted(views):
                if name != canary and views[name].newest != target:
                    self.audit.record_hold(
                        name, rule="canary-baking",
                        inputs={"canary": canary, "target": target})
            return admitted
        upgrading = [name for name, view in views.items()
                     if name != canary and view.ds_found
                     and view.newest == target
                     and not view.done_on(target)]
        slots = self.policy.max_concurrent_regions - len(upgrading)
        candidates = [name for name in views
                      if name != canary
                      and views[name].reachable
                      and views[name].ds_found
                      and views[name].newest != target]
        candidates = self._wave_order(views, candidates)
        if not self.policy.follow_the_sun:
            candidates.sort()
        for name in candidates:
            if slots <= 0:
                self.audit.record_hold(
                    name, rule="region-concurrency",
                    inputs={"maxConcurrentRegions":
                            self.policy.max_concurrent_regions})
                continue
            if not self._in_trough(views[name], now):
                self.audit.record_hold(
                    name, rule="awaiting-trough",
                    inputs={"utilization": views[name].utilization,
                            "troughUtilization":
                            self.policy.trough_utilization})
                continue
            if self._preflight_defers(name):
                continue
            if self._holder_defers(views, name):
                continue
            if not self._preshift_gate(views, name, target, now):
                continue
            if self._roll(name, target, rule="follow-the-sun"):
                admitted.append(name)
                views[name].newest = target
                slots -= 1
                self._trough_wait_started.pop(name, None)
        return admitted

    def _holder_defers(self, views: "dict[str, RegionView]",
                       region: str) -> bool:
        """A region currently hosting another region's pre-shifted
        sessions (it holds a live reservation) must not itself be
        admitted: its reserved capacity is spoken for, and disrupting
        it would drop exactly the sessions the pair protects. The
        release sweep frees it once the source quiesces (audited,
        bounded by the source's own rollout — no extra liveness knob
        needed)."""
        if not views[region].preshift_reservation:
            return False
        source = ""
        parsed = self._parse_reservation(
            views[region].preshift_reservation)
        if parsed is not None:
            source = parsed[0]
        self.preshift_holds_total += 1
        self.audit.record_hold(
            region, rule="reserve-holder",
            inputs={"source": source})
        return True

    def _in_trough(self, view: RegionView, now: float) -> bool:
        """Follow-the-sun gate: the region's live utilization must be
        at or below the trough threshold — with a bounded wait, so a
        region that never quiets still upgrades (in-memory bookkeeping:
        a controller restart restarts the wait, delaying liveness by at
        most one more wait window, never violating safety)."""
        if not self.policy.follow_the_sun or view.utilization is None:
            return True
        paused = (view.capacity is not None
                  and bool(view.capacity.get("paused")))
        if not paused \
                and view.utilization <= self.policy.trough_utilization:
            return True
        # A region whose OWN capacity controller is hard-pausing at
        # peak is never "in trough" regardless of the utilization
        # number — the richer status block vetoes the threshold, while
        # the bounded wait still guarantees liveness (admission only
        # rolls the DS; the region's controller keeps modulating its
        # internal waves after the wait expires).
        started = self._trough_wait_started.setdefault(
            view.name, now)
        return now - started >= self.policy.max_trough_wait_seconds

    def _roll(self, region: str, target: str, rule: str) -> bool:
        handle = self.regions[region]
        try:
            handle.roll_to(target)
        except _TRANSIENTS as exc:
            logger.warning("admission roll of region %s to %s "
                           "deferred: %s", region, target, exc)
            return False
        if self.watch:
            # the roll made ``target`` the newest revision
            # synchronously; tell the watcher so a delayed DS event
            # cannot make the next pass re-admit this region
            self._watchers[region].note_rolled(target)
        self.admissions_total += 1
        self.audit.record(
            "fed-admit", region, decision=f"rolled to {target}",
            rule=rule, inputs={"target": target})
        logger.info("federation: region %s admitted to revision %s "
                    "(%s)", region, target, rule)
        return True

    def _patch_region(self, region: str,
                      annotations: "dict[str, Optional[str]]") -> None:
        """Single write seam for region DS annotations: in watch mode
        the write goes through the RegionWatcher so it lands in the
        own-write journal (the next pass trusts the stamped truth even
        while the MODIFIED event is delayed); ``None`` deletes a key.
        Transients propagate — callers keep defer-and-retry."""
        if self.watch:
            self._watchers[region].patch_annotations(annotations)
            return
        handle = self.regions[region]
        handle.client.patch_daemon_set_annotations(
            handle.namespace, handle.ds_name, annotations)

    # ------------------------------------------------------------------
    # cross-region session pre-shift (PrewarmCoordinator at region
    # granularity: reserve crash-ordered before ready, released in ONE
    # patch, zero residue — the stamps ARE the state machine)
    # ------------------------------------------------------------------
    @staticmethod
    def _parse_reservation(
            value: str) -> "Optional[tuple[str, str, int, float]]":
        """``<source>:<revision>:<slots>:<epoch>`` or None."""
        parts = value.split(":")
        if len(parts) != 4:
            return None
        try:
            return parts[0], parts[1], int(parts[2]), float(parts[3])
        except ValueError:
            return None

    @staticmethod
    def _parse_ready(
            value: str) -> "Optional[tuple[str, str, float]]":
        """``<source>:<revision>:<epoch>`` or None."""
        parts = value.split(":")
        if len(parts) != 3:
            return None
        try:
            return parts[0], parts[1], float(parts[2])
        except ValueError:
            return None

    def _preshift_sweep(self, views: "dict[str, RegionView]",
                        target: str, now: float,
                        halted: bool) -> None:
        """Release reservation→ready pairs whose source region's
        admission arc is over. The reserve is held while the source is
        DISRUPTING (nodes out, mid-upgrade, mid-rollback — shifted
        sessions still live on the reserve) and while the source is
        PENDING admission to the reserved revision (the gate stamped
        it; the roll follows when readiness lands). Everything else —
        source converged, rolled back and quiesced, target moved on,
        source gone, stamp corrupt — releases BOTH stamps in one
        patch, so no pass boundary can observe a half-released pair
        and a converged fleet carries zero residue (the fsck gate)."""
        for name in sorted(views):
            view = views[name]
            if not view.reachable or not view.preshift_reservation:
                continue
            parsed = self._parse_reservation(view.preshift_reservation)
            source = parsed[0] if parsed else ""
            release = False
            if parsed is None:
                release = True  # corrupt stamp (fsck would drop it)
            else:
                revision = parsed[1]
                src = views.get(source)
                if src is None:
                    release = True  # source left the fleet: orphan
                elif not src.reachable:
                    continue  # stale info: never release blind
                elif revision != target:
                    # stale pair: the target moved on, so the stale
                    # arc can never resume — its share is revoked
                    # (decrease-immediate) and its operator freezes.
                    # Release once the revocation is VISIBLE on the
                    # source's stamp and its capacity is whole; a
                    # fresh pair protects the source's admission to
                    # the new target. Waiting for full node-DONE
                    # quiescence here would deadlock: a region frozen
                    # mid-upgrade by a promotion only recovers via an
                    # admission the held reserve may itself block.
                    release = (not src.share
                               and src.unavailable == 0)
                else:
                    # quiesced: every node DONE and back in service —
                    # the source's sessions have capacity at home again
                    quiesced = (src.total > 0
                                and src.nodes_done == src.total
                                and src.unavailable == 0)
                    # mid-arc: admitted to the reserved revision but
                    # pods not all Ready yet
                    mid_arc = (src.newest == revision
                               and not src.done_on(revision))
                    # pending: the gate stamped this pair and the roll
                    # follows once readiness lands
                    pending = (not halted
                               and src.newest != revision)
                    release = quiesced and not mid_arc and not pending
            if not release:
                continue
            try:
                self._patch_region(name, {
                    self.keys.preshift_reservation_annotation: None,
                    self.keys.preshift_ready_annotation: None})
            except _TRANSIENTS as exc:
                logger.warning("pre-shift release on region %s "
                               "deferred: %s", name, exc)
                continue
            view.preshift_reservation = ""
            view.preshift_ready = ""
            self.preshift_released_total += 1
            if source:
                self._preshift_wait_started.pop(source, None)
            self.audit.record(
                "fed-preshift", name,
                decision=f"released reserve held for {source or '?'}",
                rule="preshift-release",
                inputs={"source": source})
            logger.info("federation: released pre-shift reserve on "
                        "region %s (source %s quiesced)", name,
                        source or "?")

    def _pick_reserve(self, views: "dict[str, RegionView]",
                      source: str,
                      target: str) -> "tuple[list[str], list[str]]":
        """(eligible, free) reserve regions for ``source``. Eligible:
        reachable, DS present, and not mid-upgrade on the target (a
        region whose own capacity is shrinking cannot absorb shifted
        sessions). Free: eligible and not already holding a
        reservation (one pair per reserve DS — holder-busy defers).
        Preference order inside ``free``: the canary region LAST no
        matter what (it is the first region disrupted on every future
        revision, so a pair parked there blocks the very admission
        that would release it), then regions already converged on the
        target first (they will not be disrupted again this rollout),
        then HIGHEST utilization — follow-the-sun admits the quiet
        regions first, so the busiest region is admitted last and
        stays stable as a reserve — ties by name."""
        canary = self._canary_region(views) if views else ""
        eligible: "list[str]" = []
        for name in sorted(views):
            if name == source:
                continue
            view = views[name]
            if not view.reachable or not view.ds_found:
                continue
            if view.newest == target and not view.done_on(target):
                continue
            eligible.append(name)
        free = [name for name in eligible
                if not views[name].preshift_reservation]
        def rank(name: str) -> tuple:
            view = views[name]
            u = view.utilization
            return (1 if name == canary else 0,
                    0 if view.done_on(target) else 1,
                    -(round(u, 6) if u is not None else -1.0), name)
        free.sort(key=rank)
        return eligible, free

    def _preshift_gate(self, views: "dict[str, RegionView]",
                       region: str, target: str, now: float) -> bool:
        """Zero-drop admission gate: True only once an adjacent region
        holds a READY reservation for this region's sessions (or the
        policy/fleet shape makes pre-shift moot). Crash-restart
        resumes from the stamps alone: an existing reservation for
        (region, target) is adopted, never re-stamped."""
        policy = self.policy
        if not policy.session_pre_shift:
            return True
        handle = self.regions[region]
        slots: "Optional[int]" = None
        if handle.sessions is not None:
            try:
                slots = int(handle.sessions())
            except Exception:  # noqa: BLE001 — a broken signal must
                slots = None  # not wedge the rollout
        if slots is None:
            slots = views[region].total  # census: conservative proxy
        if slots <= 0:
            return True
        holder = ""
        reserved_slots, reserved_at = slots, now
        for name in sorted(views):
            if name == region:
                continue
            parsed = self._parse_reservation(
                views[name].preshift_reservation)
            if parsed is not None and parsed[0] == region \
                    and parsed[1] == target:
                holder = name
                reserved_slots, reserved_at = parsed[2], parsed[3]
                break
        if not holder:
            eligible, free = self._pick_reserve(views, region, target)
            if not eligible:
                # a fleet with no possible spare can never pre-shift;
                # admit (audited) rather than park the rollout forever
                self.audit.record(
                    "fed-preshift", region,
                    decision="admitted without reserve",
                    rule="preshift-no-reserve",
                    inputs={"slots": slots})
                return True
            if not free:
                return self._preshift_hold(
                    region, now, holder="", slots=slots,
                    why="holder-busy")
            reserve = free[0]
            value = f"{region}:{target}:{slots}:{now:g}"
            try:
                self._patch_region(reserve, {
                    self.keys.preshift_reservation_annotation: value})
            except _TRANSIENTS as exc:
                logger.warning("pre-shift reservation on region %s "
                               "deferred: %s", reserve, exc)
                return self._preshift_hold(
                    region, now, holder=reserve, slots=slots,
                    why="reservation-write-deferred")
            views[reserve].preshift_reservation = value
            self.preshift_reservations_total += 1
            self.audit.record(
                "fed-preshift", region,
                decision=f"reserved {slots} slot(s) in {reserve}",
                rule="preshift-reserve",
                inputs={"reserve": reserve, "slots": slots})
            holder, reserved_slots, reserved_at = reserve, slots, now
        ready_stamp = self._parse_ready(views[holder].preshift_ready)
        if ready_stamp is not None and ready_stamp[0] == region \
                and ready_stamp[1] == target:
            self._preshift_wait_started.pop(region, None)
            return True
        hook = self.regions[holder].preshift_ready
        hook_ready = True  # no warmup signal = nothing to warm
        if hook is not None:
            try:
                hook_ready = bool(hook(reserved_slots, reserved_at))
            except Exception:  # noqa: BLE001 — a broken hook must not
                hook_ready = True  # wedge the rollout (prewarm posture)
        if hook_ready:
            value = f"{region}:{target}:{now:g}"
            try:
                self._patch_region(holder, {
                    self.keys.preshift_ready_annotation: value})
            except _TRANSIENTS as exc:
                logger.warning("pre-shift ready stamp on region %s "
                               "deferred: %s", holder, exc)
                return self._preshift_hold(
                    region, now, holder=holder, slots=reserved_slots,
                    why="ready-write-deferred")
            views[holder].preshift_ready = value
            self.preshift_ready_total += 1
            self._preshift_wait_started.pop(region, None)
            self.audit.record(
                "fed-preshift", region,
                decision=f"reserve {holder} ready",
                rule="preshift-ready",
                inputs={"reserve": holder, "slots": reserved_slots})
            return True
        return self._preshift_hold(
            region, now, holder=holder, slots=reserved_slots,
            why="warming")

    def _preshift_hold(self, region: str, now: float, holder: str,
                       slots: int, why: str) -> bool:
        """Bounded pre-shift wait (liveness): holds are audited, and a
        region that cannot reach a ready reserve within
        ``maxPreshiftWaitSeconds`` is admitted anyway (audited) — a
        missing or never-warming spare must not park the rollout.
        In-memory bookkeeping: a controller restart restarts the wait,
        delaying liveness by at most one window, never safety."""
        started = self._preshift_wait_started.setdefault(region, now)
        if now - started >= self.policy.max_preshift_wait_seconds:
            self.preshift_expired_waits_total += 1
            self._preshift_wait_started.pop(region, None)
            self.audit.record(
                "fed-preshift", region,
                decision="admitted after pre-shift wait expired",
                rule="preshift-wait-expired",
                inputs={"waitedSeconds": round(now - started, 1),
                        "reserve": holder or None, "why": why})
            logger.warning(
                "federation: region %s admitted after %ds pre-shift "
                "wait (%s) — sessions may drop", region,
                int(now - started), why)
            return True
        self.preshift_holds_total += 1
        self.audit.record_hold(
            region, rule="awaiting-preshift",
            inputs={"reserve": holder or None, "slots": slots,
                    "why": why})
        return False

    # ------------------------------------------------------------------
    # budget shares (the lifted PR 7 ledger)
    # ------------------------------------------------------------------
    def _global_budget(self, views: "dict[str, RegionView]") -> int:
        total = 0
        for name in self.regions:
            view = views.get(name)
            if view is not None and view.reachable:
                total += view.total
            else:
                total += self._region_totals.get(name, 0)
        return scaled_value_from_int_or_percent(
            self.policy.global_max_unavailable, total, round_up=True)

    def _maintain_shares(self, views: "dict[str, RegionView]",
                         canary: str, target: str,
                         admitted: "list[str]") -> "dict[str, int]":
        """Plan and stamp the per-region shares: active regions (DS on
        target, not yet converged — including this pass's admissions)
        split the global budget; everyone else is entitled to 0.
        Decreases stamp immediately; raises only in a pass where EVERY
        region's stamp was read fresh and the raised sum still fits
        (the ledger's raise gate) — the write-side half of
        decrease-immediate/increase-next-pass."""
        fleet = sorted(self.regions)
        global_budget = self._global_budget(views)
        active: dict[str, int] = {}
        for name in fleet:
            view = views[name]
            total = view.total if view.reachable \
                else self._region_totals.get(name, 0)
            if total <= 0:
                continue
            if name in admitted or (view.ds_found
                                    and view.newest == target
                                    and not view.done_on(target)):
                active[name] = total
            elif target in view.quarantined and (
                    view.unavailable > 0 or view.nodes_done < view.total):
                # a halted region mid-rollback keeps its share: the
                # rollback arc needs budget to evacuate the bad hash
                active[name] = total
        desired = self.ledger.plan(active, global_budget) if active \
            else {}
        fresh = {name: (views[name].share or 0)
                 for name in fleet if views[name].reachable}
        froze = False
        shares: dict[str, int] = {}
        for name in fleet:
            view = views[name]
            current = view.share
            want = desired.get(name, 0)
            shares[name] = want
            if not view.reachable:
                continue
            if current is None and want == 0:
                continue  # never-granted regions need no zero stamp
            if current == want:
                continue
            if want > (current or 0):
                if not self.ledger.raise_allowed(
                        name, want, fresh, fleet, global_budget):
                    froze = True
                    self.audit.record_hold(
                        name, rule="share-raise-frozen",
                        inputs={"want": want, "recorded": current})
                    shares[name] = current or 0
                    continue
            if self._stamp_share(name, want):
                fresh[name] = want
            else:
                shares[name] = current or 0
        if froze:
            self.raise_freeze_passes_total += 1
        return shares

    def _stamp_share(self, region: str, share: int) -> bool:
        try:
            self._patch_region(region, {
                self.keys.budget_share_annotation: str(share)})
        except _TRANSIENTS as exc:
            logger.warning("share stamp for region %s deferred: %s",
                           region, exc)
            return False
        self.share_stamps_total += 1
        self.audit.record(
            "fed-share", region, decision=f"share={share}",
            rule="ledger-split", inputs={"share": share})
        return True

    # ------------------------------------------------------------------
    # explain (obs/ public API, region granularity)
    # ------------------------------------------------------------------
    def explain_region(self, region: str) -> dict:
        """Why is this region not upgrading — and what has the
        federation decided about it? Answered from the last pass's
        in-memory views plus the decision-audit ring (no cluster read,
        the node-level ``explain`` contract)."""
        out: dict = {"region": region, "blocking": []}
        chain: "list[str]" = out["blocking"]
        view = self._last_views.get(region)
        target = self._last_target
        status = self.last_status or {}
        if region not in self.regions:
            chain.append(f"unknown region {region!r} (known: "
                         f"{sorted(self.regions)})")
            return out
        if view is None:
            chain.append("no federation pass has read this region yet "
                         "this incarnation")
            return out
        out["phase"] = (status.get("regions", {})
                        .get(region, {}).get("phase", "unknown"))
        canary = status.get("canaryRegion", "")
        if not view.reachable:
            chain.append("partitioned from the federation layer: the "
                         "freshness probe did not read back — no "
                         "admission and no share raise anywhere until "
                         "the fleet reads fresh")
        if status.get("halted"):
            chain.append(f"revision {target!r} is quarantined "
                         f"fleet-wide: the canary region's guard "
                         f"condemned it; no region admits it again")
        elif view.done_on(target):
            chain.append("rollout complete on the target revision — "
                         "nothing blocking")
        elif view.newest == target:
            if region == canary and not status.get("baked"):
                chain.append("canary region mid-bake: the fleet waves "
                             "open only after every node is done and "
                             f"{self.policy.bake_seconds}s have "
                             "elapsed past the durable bake stamp")
            else:
                chain.append(f"upgrading under a budget share of "
                             f"{view.share or 0} node(s)")
        else:
            forecast = self.last_preflight.get(region)
            if forecast is not None \
                    and forecast["verdict"] == "reject":
                chain.append(
                    f"preflight rejected the region admission "
                    f"({', '.join(forecast['breaches'])}): forecast "
                    f"SLO risk {forecast['sloRiskFraction']:g} over a "
                    f"{forecast['horizonSeconds']:.0f}s horizon — no "
                    f"roll and no budget-share stamp until the "
                    f"forecast clears")
            if region in self._preshift_wait_started:
                chain.append(
                    "holding for session pre-shift: no reserve region "
                    "has a ready reservation for its sessions yet "
                    "(bounded by maxPreshiftWaitSeconds="
                    f"{self.policy.max_preshift_wait_seconds})")
            if region != canary and not status.get("baked"):
                chain.append(f"held behind the canary region "
                             f"{canary!r}: the target revision lacks "
                             f"the fleet bake-passed stamp")
            elif view.utilization is not None \
                    and view.utilization > self.policy.trough_utilization:
                chain.append(f"awaiting its traffic trough "
                             f"(utilization {view.utilization:.2f} > "
                             f"{self.policy.trough_utilization:g})")
            else:
                chain.append("awaiting a region wave slot "
                             f"(maxConcurrentRegions="
                             f"{self.policy.max_concurrent_regions})")
        out["records"] = [rec.as_dict() for rec
                          in self.audit.records_for(region, limit=6)]
        return out

    def status(self) -> dict:
        """The last pass's status block (``{}`` before the first)."""
        return dict(self.last_status or {})
