"""FederationBudgetLedger: the global→per-region disruption budget.

The PR 7 shard ledger proved the pattern inside one cluster: a global
``maxUnavailable`` split deterministically into durable per-partition
shares, spent under decrease-immediate/increase-next-pass with a global
clamp, so concurrent owners never jointly overdraw across takeovers.
This module lifts the same ledger one level — the partition key is a
REGION (a whole cluster), and each region's share lives as ONE
annotation on that region's own runtime DaemonSet:

- the region operator's effective ``maxUnavailable`` IS its stamp
  (absent or 0 = the region admits nothing), so the global inequality
  is enforced region-locally, even while the region is partitioned
  from the federation layer or its controller is being replaced;
- the federation controller stamps DECREASES immediately and stamps a
  RAISE only in a pass where every region's stamp was freshly read
  back and the raised sum still fits under the global budget — the
  write-side dual of :func:`tpu_operator_libs.k8s.sharding.
  ledger_spend_cap`, and the reason a freshly-recovered federation
  controller (which knows nothing but what the regions' stamps say)
  can never let two regions jointly overdraw.

"Freshly read" is a pluggable contract, not necessarily a GET: in the
polled read path it means the per-pass probe annotation read back; in
the watch-driven path (federation/region_watch.py) it means the
region's probe ECHO — the probe's own MODIFIED event observed back
through the watch stream — is within the policy's staleness bound,
with the own-write journal guaranteeing the controller's own share
stamps are never summed stale while their events are still in flight.
Either way the raise gate's invariant is the same: no raise anywhere
until every region's stamp is trusted current.

The arithmetic (largest-remainder proportional split) is shared with
the shard ledger via :func:`~tpu_operator_libs.k8s.sharding.
split_budget`, which is key-type generic for exactly this reason.
"""

from __future__ import annotations

import logging
from typing import Optional

from tpu_operator_libs.consts import FederationKeys
from tpu_operator_libs.k8s.sharding import split_budget

logger = logging.getLogger(__name__)


class FederationBudgetLedger:
    """Encode/decode/plan the durable per-region budget shares."""

    def __init__(self, keys: Optional[FederationKeys] = None) -> None:
        self._keys = keys or FederationKeys()

    @property
    def annotation_key(self) -> str:
        return self._keys.budget_share_annotation

    def share_from(self,
                   annotations: "dict[str, str]") -> Optional[int]:
        """The region's recorded share, or None when never stamped (a
        malformed stamp also reads as None — the region then admits
        nothing, the conservative side)."""
        raw = annotations.get(self._keys.budget_share_annotation)
        if raw is None:
            return None
        try:
            return max(0, int(raw))
        except ValueError:
            logger.warning("ignoring malformed budget share %r", raw)
            return None

    def plan(self, active_counts: "dict[str, int]",
             global_budget: int) -> "dict[str, int]":
        """Deterministic split of ``global_budget`` across the regions
        currently spending (region name -> managed node count), each
        share additionally capped at the region's own size (a share
        beyond the region's node count can never be spent and would
        only pad the global clamp). Inactive regions are entitled to
        0 by definition — pass only the active census."""
        shares = split_budget(global_budget, active_counts)
        return {region: min(share, active_counts[region])
                for region, share in shares.items()}

    @staticmethod
    def raise_allowed(region: str, proposed: int,
                      fresh: "dict[str, int]",
                      fleet: "list[str]",
                      global_budget: int) -> bool:
        """May ``region``'s stamp be RAISED to ``proposed`` this pass?

        ``fresh`` maps each region whose DaemonSet was read FRESH this
        pass (probe write landed and read back) to its recorded stamp,
        with an absent annotation reading as 0 — truthful, because only
        the federation controller ever writes these stamps. A raise is
        allowed only when every fleet region was read fresh and the
        proposed sum still fits: one partitioned region freezes raises
        fleet-wide, because a stale read could hide a stamp a previous
        federation incarnation already granted. Decreases never consult
        this gate — they only tighten the inequality.
        """
        total = proposed
        for other in fleet:
            if other == region:
                continue
            stamp = fresh.get(other)
            if stamp is None:
                return False
            total += stamp
        return total <= global_budget
