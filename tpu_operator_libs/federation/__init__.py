"""Multi-cluster federation: region-as-canary global rollouts.

The production topology that serves millions of users is many clusters
across regions, each already running this library's per-cluster
operator. This package is the layer above them — a federation
controller that treats whole clusters/regions as ring members and
drives each region purely through the CRD/policy surface its operator
already consumes:

- :class:`~tpu_operator_libs.federation.controller.
  FederationController` — region-as-canary waves (one low-traffic
  region bakes every revision behind a durable bake stamp before the
  fleet), fleet-wide quarantine lifted from the canary region's own
  RolloutGuard verdict, follow-the-sun admission ordering from each
  region's live capacity signal, and partition-safe freshness probing.
- :class:`~tpu_operator_libs.federation.ledger.
  FederationBudgetLedger` — the PR 7 shard-budget ledger lifted one
  level: a GLOBAL disruption budget split into durable per-region
  share stamps, spent under decrease-immediate/increase-next-pass with
  a raise gate that freezes fleet-wide while any region reads stale.
- :class:`~tpu_operator_libs.federation.region_watch.RegionWatcher` —
  the O(changed-regions) read path: per-region watch streams feeding
  informer caches, so a 50-region steady-state pass reads only the
  regions whose streams delivered events, with a staleness bound on
  each region's change cursor standing in for the per-pass freshness
  probe round-trip.

Robustness is the headline property, so the subsystem ships inside a
standing chaos gate from day one: ``make test-federation`` drives a
multi-cluster :class:`~tpu_operator_libs.chaos.federation.
FederationFleetSim` (every region a real FakeCluster + operator
incarnation) through regional-controller kills, federation↔region
partitions and federation-controller kills, with the ``global-budget``,
``canary-containment`` and ``federation-resume`` invariants always on
(docs/federation.md).
"""

from tpu_operator_libs.api.federation_policy import FederationPolicySpec
from tpu_operator_libs.federation.controller import (
    FederationController,
    RegionHandle,
    RegionView,
)
from tpu_operator_libs.federation.ledger import FederationBudgetLedger
from tpu_operator_libs.federation.region_watch import RegionWatcher

__all__ = [
    "FederationBudgetLedger",
    "FederationController",
    "FederationPolicySpec",
    "RegionHandle",
    "RegionView",
    "RegionWatcher",
]
