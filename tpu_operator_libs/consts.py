"""State names, label/annotation key formats and log levels.

TPU-native analogue of the reference's ``pkg/upgrade/consts.go`` and
``pkg/consts/consts.go``.  Two deliberate departures from the reference:

- Keys live under the ``google.com`` / ``cloud.google.com`` label domains and
  default to the ``libtpu`` runtime name (reference keys:
  ``nvidia.com/%s-driver-upgrade-state`` etc., pkg/upgrade/consts.go:21-41).
- Key construction is *instance-scoped* via :class:`UpgradeKeys` rather than a
  process-global mutable driver name (the reference's ``DriverName`` global,
  pkg/upgrade/util.go:87-95, makes one process unable to manage two
  accelerator runtimes; we need GPU+TPU in one cluster).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class LogLevel(enum.IntEnum):
    """Semantic log levels mapped onto Python logging levels.

    The reference maps semantic levels to logr verbosity
    (pkg/consts/consts.go:24-29: Error=-2, Warning=-1, Info=0, Debug=1).
    Python's logging has a native severity scale, so we use it directly.
    """

    ERROR = 40
    WARNING = 30
    INFO = 20
    DEBUG = 10


class UpgradeState(str, enum.Enum):
    """Per-node upgrade states, durably recorded as a node label value.

    Mirrors the 11 states of the reference state machine
    (pkg/upgrade/consts.go:42-67).  The state label on the Node object *is*
    the durable store: there is no database, and every reconcile rebuilds the
    cluster picture from these labels (upgrade_state.go:68-72).
    """

    # Node not yet processed, or upgrade flow disabled. Stored as the absence
    # of the label / empty value (consts.go:42-43).
    UNKNOWN = ""
    # Runtime pod on the node is out of date; no action taken yet.
    UPGRADE_REQUIRED = "upgrade-required"
    # Node must be made unschedulable before the runtime upgrade.
    CORDON_REQUIRED = "cordon-required"
    # Wait (up to a timeout) for workload jobs on the node to finish.
    WAIT_FOR_JOBS_REQUIRED = "wait-for-jobs-required"
    # Selected workload pods must be deleted before the upgrade proceeds.
    POD_DELETION_REQUIRED = "pod-deletion-required"
    # Node must be drained (cordon + evict remaining workload pods).
    DRAIN_REQUIRED = "drain-required"
    # Runtime pod must be restarted (or safe-load unblocked) to pick up the
    # new DaemonSet revision.
    POD_RESTART_REQUIRED = "pod-restart-required"
    # Post-upgrade validation (validation pod ready / ICI fabric healthy)
    # must pass before the node returns to service.
    VALIDATION_REQUIRED = "validation-required"
    # Upgrade complete; node must be made schedulable again.
    UNCORDON_REQUIRED = "uncordon-required"
    # Runtime pod up to date and ready; node schedulable.
    DONE = "upgrade-done"
    # Any failure during the upgrade; auto-recovers when the pod is healthy.
    FAILED = "upgrade-failed"

    def __str__(self) -> str:  # label values are plain strings
        return self.value


#: States that count as "upgrade in progress" — everything except the three
#: idle buckets (unknown / done / upgrade-required), mirroring
#: GetUpgradesInProgress (upgrade_state.go:1055-1062).
IN_PROGRESS_STATES = (
    UpgradeState.CORDON_REQUIRED,
    UpgradeState.WAIT_FOR_JOBS_REQUIRED,
    UpgradeState.POD_DELETION_REQUIRED,
    UpgradeState.DRAIN_REQUIRED,
    UpgradeState.POD_RESTART_REQUIRED,
    UpgradeState.VALIDATION_REQUIRED,
    UpgradeState.UNCORDON_REQUIRED,
    UpgradeState.FAILED,
)

#: Every state bucket, in the fixed order ApplyState processes them
#: (upgrade_state.go:418-481). Used for census logging and counters.
ALL_STATES = (
    UpgradeState.UNKNOWN,
    UpgradeState.DONE,
    UpgradeState.UPGRADE_REQUIRED,
    UpgradeState.CORDON_REQUIRED,
    UpgradeState.WAIT_FOR_JOBS_REQUIRED,
    UpgradeState.POD_DELETION_REQUIRED,
    UpgradeState.DRAIN_REQUIRED,
    UpgradeState.POD_RESTART_REQUIRED,
    UpgradeState.FAILED,
    UpgradeState.VALIDATION_REQUIRED,
    UpgradeState.UNCORDON_REQUIRED,
)

#: The legal transitions of the state machine, with the condition that
#: takes each edge — the single source of truth for the graph. The e2e
#: suite asserts every transition observed in full simulated upgrades is
#: one of these edges, and docs/state-diagram.{dot,svg} are generated
#: from this table (tools/state_diagram.py) with a drift-check test, so
#: the diagram can never go stale the way the reference's PNG did
#: (docs/automatic-ofed-upgrade.md:85 marks it outdated). Transitions
#: mirror upgrade_state.go (SURVEY.md §1 diagram).
STATE_EDGES: tuple[tuple[UpgradeState, UpgradeState, str], ...] = (
    (UpgradeState.UNKNOWN, UpgradeState.DONE,
     "runtime pod in sync with DaemonSet"),
    (UpgradeState.UNKNOWN, UpgradeState.UPGRADE_REQUIRED,
     "pod outdated | safe-load wait | upgrade-requested"),
    (UpgradeState.DONE, UpgradeState.UPGRADE_REQUIRED,
     "new DS revision | safe-load wait | upgrade-requested"),
    (UpgradeState.UPGRADE_REQUIRED, UpgradeState.CORDON_REQUIRED,
     "slot available (throttle + slice planner)"),
    (UpgradeState.CORDON_REQUIRED, UpgradeState.WAIT_FOR_JOBS_REQUIRED,
     "cordoned"),
    (UpgradeState.WAIT_FOR_JOBS_REQUIRED, UpgradeState.POD_DELETION_REQUIRED,
     "jobs done | timeout (pod deletion enabled)"),
    (UpgradeState.WAIT_FOR_JOBS_REQUIRED, UpgradeState.DRAIN_REQUIRED,
     "jobs done | timeout (pod deletion disabled)"),
    (UpgradeState.POD_DELETION_REQUIRED, UpgradeState.POD_RESTART_REQUIRED,
     "filtered pods evicted (checkpoint gate passed)"),
    (UpgradeState.POD_DELETION_REQUIRED, UpgradeState.DRAIN_REQUIRED,
     "eviction failed, drain enabled"),
    (UpgradeState.POD_DELETION_REQUIRED, UpgradeState.FAILED,
     "eviction failed, drain disabled"),
    (UpgradeState.DRAIN_REQUIRED, UpgradeState.POD_RESTART_REQUIRED,
     "drain succeeded"),
    (UpgradeState.DRAIN_REQUIRED, UpgradeState.FAILED, "drain failed"),
    (UpgradeState.POD_RESTART_REQUIRED, UpgradeState.VALIDATION_REQUIRED,
     "new pod in sync & ready (validation enabled)"),
    (UpgradeState.POD_RESTART_REQUIRED, UpgradeState.UNCORDON_REQUIRED,
     "new pod in sync & ready (was schedulable)"),
    (UpgradeState.POD_RESTART_REQUIRED, UpgradeState.DONE,
     "new pod in sync & ready (was cordoned before upgrade)"),
    (UpgradeState.POD_RESTART_REQUIRED, UpgradeState.FAILED,
     "pod crash-looping (>10 restarts)"),
    (UpgradeState.VALIDATION_REQUIRED, UpgradeState.UNCORDON_REQUIRED,
     "validation passed (was schedulable)"),
    (UpgradeState.VALIDATION_REQUIRED, UpgradeState.DONE,
     "validation passed (was cordoned before upgrade)"),
    (UpgradeState.VALIDATION_REQUIRED, UpgradeState.FAILED,
     "600 s validation timeout"),
    (UpgradeState.UNCORDON_REQUIRED, UpgradeState.DONE, "uncordoned"),
    (UpgradeState.FAILED, UpgradeState.UNCORDON_REQUIRED,
     "pod healthy again [validated] (was schedulable)"),
    (UpgradeState.FAILED, UpgradeState.DONE,
     "pod healthy again [validated] (was cordoned before upgrade)"),
)

#: Adjacency view of STATE_EDGES, keyed by label value ("" = unknown).
LEGAL_EDGES: dict[str, frozenset[str]] = {
    src: frozenset(d.value for s, d, _ in STATE_EDGES if s.value == src)
    for src in {s.value for s, _, _ in STATE_EDGES}
}

#: Label key whose presence identifies a TPU node on GKE.
TPU_RESOURCE_NAME = "google.com/tpu"

#: GKE node labels describing TPU slice topology. Used by
#: tpu_operator_libs.topology to derive the upgrade unit (sub-slice).
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"

#: The label kubelet/DaemonSet controller stamps on DS pods with the hash of
#: the ControllerRevision they were created from (pod_manager.go:70-73).
POD_CONTROLLER_REVISION_HASH_LABEL = "controller-revision-hash"

#: Merge-patch value meaning "delete this annotation"
#: (node_upgrade_state_provider.go:147-151).
NULL_STRING = "null"
TRUE_STRING = "true"


@dataclass(frozen=True)
class UpgradeKeys:
    """Instance-scoped builder for the node label/annotation keys.

    One instance per managed accelerator runtime; default is the libtpu
    runtime under the ``google.com`` domain.  A GPU-flavoured instance
    (``UpgradeKeys(driver="gpu", domain="nvidia.com")``) reproduces the
    reference key scheme exactly, which is how mixed GPU+TPU clusters are
    supported (BASELINE config #5).

    Reference: the seven Get*Key() builders in pkg/upgrade/util.go:97-139.
    """

    driver: str = "libtpu"
    domain: str = "google.com"

    @property
    def state_label(self) -> str:
        """Node label carrying the upgrade state (consts.go:20-21)."""
        return f"{self.domain}/{self.driver}-upgrade-state"

    @property
    def skip_label(self) -> str:
        """Node label opting a node out of upgrades (consts.go:22-23)."""
        return f"{self.domain}/{self.driver}-upgrade.skip"

    @property
    def wait_for_safe_load_annotation(self) -> str:
        """Annotation the runtime init container sets to request a safe
        (cordoned + drained) first load (consts.go:24-27)."""
        return f"{self.domain}/{self.driver}-upgrade.wait-for-safe-load"

    @property
    def initial_state_annotation(self) -> str:
        """Annotation remembering the node was already unschedulable when the
        upgrade started, so it is not uncordoned at the end
        (consts.go:28-30)."""
        return f"{self.domain}/{self.driver}-upgrade.node-initial-state.unschedulable"

    @property
    def pod_completion_start_annotation(self) -> str:
        """Annotation checkpointing the wall-clock start of the
        wait-for-jobs timeout across reconciles (consts.go:31-34)."""
        return f"{self.domain}/{self.driver}-upgrade.wait-for-pod-completion-start-time"

    @property
    def validation_start_annotation(self) -> str:
        """Annotation checkpointing the start of the validation timeout
        (consts.go:35-37)."""
        return f"{self.domain}/{self.driver}-upgrade.validation-start-time"

    @property
    def upgrade_requested_annotation(self) -> str:
        """Annotation requesting an on-demand upgrade (the only trigger for
        orphaned pods, whose revision hash cannot be compared)
        (consts.go:38-41)."""
        return f"{self.domain}/{self.driver}-upgrade-requested"

    @property
    def event_reason(self) -> str:
        """Reason string attached to Kubernetes events (util.go:136-139)."""
        return f"{self.driver.upper()}RuntimeUpgrade"


#: Field selector template filtering pods by the node they run on
#: (consts.go:70-73).
NODE_NAME_FIELD_SELECTOR_FMT = "spec.nodeName={}"
