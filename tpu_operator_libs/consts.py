"""State names, label/annotation key formats and log levels.

TPU-native analogue of the reference's ``pkg/upgrade/consts.go`` and
``pkg/consts/consts.go``.  Two deliberate departures from the reference:

- Keys live under the ``google.com`` / ``cloud.google.com`` label domains and
  default to the ``libtpu`` runtime name (reference keys:
  ``nvidia.com/%s-driver-upgrade-state`` etc., pkg/upgrade/consts.go:21-41).
- Key construction is *instance-scoped* via :class:`UpgradeKeys` rather than a
  process-global mutable driver name (the reference's ``DriverName`` global,
  pkg/upgrade/util.go:87-95, makes one process unable to manage two
  accelerator runtimes; we need GPU+TPU in one cluster).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class LogLevel(enum.IntEnum):
    """Semantic log levels mapped onto Python logging levels.

    The reference maps semantic levels to logr verbosity
    (pkg/consts/consts.go:24-29: Error=-2, Warning=-1, Info=0, Debug=1).
    Python's logging has a native severity scale, so we use it directly.
    """

    ERROR = 40
    WARNING = 30
    INFO = 20
    DEBUG = 10


class UpgradeState(str, enum.Enum):
    """Per-node upgrade states, durably recorded as a node label value.

    Mirrors the 11 states of the reference state machine
    (pkg/upgrade/consts.go:42-67).  The state label on the Node object *is*
    the durable store: there is no database, and every reconcile rebuilds the
    cluster picture from these labels (upgrade_state.go:68-72).
    """

    # Node not yet processed, or upgrade flow disabled. Stored as the absence
    # of the label / empty value (consts.go:42-43).
    UNKNOWN = ""
    # Runtime pod on the node is out of date; no action taken yet.
    UPGRADE_REQUIRED = "upgrade-required"
    # Node must be made unschedulable before the runtime upgrade.
    CORDON_REQUIRED = "cordon-required"
    # Wait (up to a timeout) for workload jobs on the node to finish.
    WAIT_FOR_JOBS_REQUIRED = "wait-for-jobs-required"
    # Selected workload pods must be deleted before the upgrade proceeds.
    POD_DELETION_REQUIRED = "pod-deletion-required"
    # Node must be drained (cordon + evict remaining workload pods).
    DRAIN_REQUIRED = "drain-required"
    # Runtime pod must be restarted (or safe-load unblocked) to pick up the
    # new DaemonSet revision.
    POD_RESTART_REQUIRED = "pod-restart-required"
    # Post-upgrade validation (validation pod ready / ICI fabric healthy)
    # must pass before the node returns to service.
    VALIDATION_REQUIRED = "validation-required"
    # Upgrade complete; node must be made schedulable again.
    UNCORDON_REQUIRED = "uncordon-required"
    # Runtime pod up to date and ready; node schedulable.
    DONE = "upgrade-done"
    # Any failure during the upgrade; auto-recovers when the pod is healthy.
    FAILED = "upgrade-failed"
    # The fleet halted on a bad revision (canary threshold tripped): the
    # node's runtime pod must be restarted back onto the PREVIOUS
    # ControllerRevision, revalidated, and returned to service. Entered
    # only from FAILED / VALIDATION_REQUIRED while the RolloutGuard has
    # quarantined the node's current revision (beyond-reference: the
    # reference has no notion of "the new revision itself is bad").
    ROLLBACK_REQUIRED = "rollback-required"
    # Safe mid-flight abort (beyond-reference): the fleet can no longer
    # afford this node's disruption — serving capacity collapsed under
    # it (traffic spike, concurrent node kills shrinking the effective
    # disruption budget) or the maintenance window is about to close on
    # a predicted overrun. Entered only from the DRAIN-PHASE states
    # (cordon / wait-for-jobs / pod-deletion / drain), where the node's
    # runtime is still intact; past pod restart the cheapest path back
    # to capacity is finishing. The abort halts eviction (the label
    # flip fails any in-flight worker's optimistic commit), releases
    # the serving-gate drain so its endpoints admit again, uncordons,
    # and returns the node to upgrade-required with zero cordon/stamp
    # residue — crash-ordered so an operator dying mid-abort resumes it
    # from this label alone.
    ABORT_REQUIRED = "abort-required"

    def __str__(self) -> str:  # label values are plain strings
        return self.value


#: States that count as "upgrade in progress" — everything except the three
#: idle buckets (unknown / done / upgrade-required), mirroring
#: GetUpgradesInProgress (upgrade_state.go:1055-1062).
IN_PROGRESS_STATES = (
    UpgradeState.CORDON_REQUIRED,
    UpgradeState.WAIT_FOR_JOBS_REQUIRED,
    UpgradeState.POD_DELETION_REQUIRED,
    UpgradeState.DRAIN_REQUIRED,
    UpgradeState.POD_RESTART_REQUIRED,
    UpgradeState.VALIDATION_REQUIRED,
    UpgradeState.UNCORDON_REQUIRED,
    UpgradeState.FAILED,
    UpgradeState.ROLLBACK_REQUIRED,
    UpgradeState.ABORT_REQUIRED,
)

#: The drain-phase states a mid-flight abort may interrupt: the node is
#: cordoned (or about to be) but its runtime pod has NOT been restarted
#: yet, so returning it to service costs one uncordon — nothing was
#: torn down. Past pod restart an abort would be slower than finishing.
ABORTABLE_STATES = (
    UpgradeState.CORDON_REQUIRED,
    UpgradeState.WAIT_FOR_JOBS_REQUIRED,
    UpgradeState.POD_DELETION_REQUIRED,
    UpgradeState.DRAIN_REQUIRED,
)

#: Every state bucket, in the fixed order ApplyState processes them
#: (upgrade_state.go:418-481). Used for census logging and counters.
ALL_STATES = (
    UpgradeState.UNKNOWN,
    UpgradeState.DONE,
    UpgradeState.UPGRADE_REQUIRED,
    UpgradeState.CORDON_REQUIRED,
    UpgradeState.WAIT_FOR_JOBS_REQUIRED,
    UpgradeState.POD_DELETION_REQUIRED,
    UpgradeState.DRAIN_REQUIRED,
    UpgradeState.ABORT_REQUIRED,
    UpgradeState.POD_RESTART_REQUIRED,
    UpgradeState.FAILED,
    UpgradeState.ROLLBACK_REQUIRED,
    UpgradeState.VALIDATION_REQUIRED,
    UpgradeState.UNCORDON_REQUIRED,
)

#: The legal transitions of the state machine, with the condition that
#: takes each edge — the single source of truth for the graph. The e2e
#: suite asserts every transition observed in full simulated upgrades is
#: one of these edges, and docs/state-diagram.{dot,svg} are generated
#: from this table (tools/state_diagram.py) with a drift-check test, so
#: the diagram can never go stale the way the reference's PNG did
#: (docs/automatic-ofed-upgrade.md:85 marks it outdated). Transitions
#: mirror upgrade_state.go (SURVEY.md §1 diagram).
STATE_EDGES: tuple[tuple[UpgradeState, UpgradeState, str], ...] = (
    (UpgradeState.UNKNOWN, UpgradeState.DONE,
     "runtime pod in sync with DaemonSet"),
    (UpgradeState.UNKNOWN, UpgradeState.UPGRADE_REQUIRED,
     "pod outdated | safe-load wait | upgrade-requested"),
    (UpgradeState.DONE, UpgradeState.UPGRADE_REQUIRED,
     "new DS revision | safe-load wait | upgrade-requested"),
    (UpgradeState.UPGRADE_REQUIRED, UpgradeState.CORDON_REQUIRED,
     "slot available (throttle + slice planner)"),
    (UpgradeState.CORDON_REQUIRED, UpgradeState.WAIT_FOR_JOBS_REQUIRED,
     "cordoned"),
    (UpgradeState.WAIT_FOR_JOBS_REQUIRED, UpgradeState.POD_DELETION_REQUIRED,
     "jobs done | timeout (pod deletion enabled)"),
    (UpgradeState.WAIT_FOR_JOBS_REQUIRED, UpgradeState.DRAIN_REQUIRED,
     "jobs done | timeout (pod deletion disabled)"),
    (UpgradeState.POD_DELETION_REQUIRED, UpgradeState.POD_RESTART_REQUIRED,
     "filtered pods evicted (checkpoint gate passed)"),
    (UpgradeState.POD_DELETION_REQUIRED, UpgradeState.DRAIN_REQUIRED,
     "eviction failed, drain enabled"),
    (UpgradeState.POD_DELETION_REQUIRED, UpgradeState.FAILED,
     "eviction failed, drain disabled"),
    (UpgradeState.DRAIN_REQUIRED, UpgradeState.POD_RESTART_REQUIRED,
     "drain succeeded"),
    (UpgradeState.DRAIN_REQUIRED, UpgradeState.FAILED, "drain failed"),
    (UpgradeState.POD_RESTART_REQUIRED, UpgradeState.VALIDATION_REQUIRED,
     "new pod in sync & ready (validation enabled)"),
    (UpgradeState.POD_RESTART_REQUIRED, UpgradeState.UNCORDON_REQUIRED,
     "new pod in sync & ready (was schedulable)"),
    (UpgradeState.POD_RESTART_REQUIRED, UpgradeState.DONE,
     "new pod in sync & ready (was cordoned before upgrade)"),
    (UpgradeState.POD_RESTART_REQUIRED, UpgradeState.FAILED,
     "pod crash-looping (>10 restarts)"),
    (UpgradeState.VALIDATION_REQUIRED, UpgradeState.UNCORDON_REQUIRED,
     "validation passed (was schedulable)"),
    (UpgradeState.VALIDATION_REQUIRED, UpgradeState.DONE,
     "validation passed (was cordoned before upgrade)"),
    (UpgradeState.VALIDATION_REQUIRED, UpgradeState.FAILED,
     "600 s validation timeout"),
    (UpgradeState.UNCORDON_REQUIRED, UpgradeState.DONE, "uncordoned"),
    (UpgradeState.FAILED, UpgradeState.UNCORDON_REQUIRED,
     "pod healthy again [validated] (was schedulable)"),
    (UpgradeState.FAILED, UpgradeState.DONE,
     "pod healthy again [validated] (was cordoned before upgrade)"),
    (UpgradeState.FAILED, UpgradeState.DRAIN_REQUIRED,
     "pod healthy but OUTDATED (new DS revision while failed)"),
    (UpgradeState.FAILED, UpgradeState.ROLLBACK_REQUIRED,
     "fleet halted: node's revision quarantined, rollback enabled"),
    (UpgradeState.VALIDATION_REQUIRED, UpgradeState.ROLLBACK_REQUIRED,
     "fleet halted: node's revision quarantined, rollback enabled"),
    (UpgradeState.ROLLBACK_REQUIRED, UpgradeState.VALIDATION_REQUIRED,
     "pod back on previous revision & ready (validation enabled)"),
    (UpgradeState.ROLLBACK_REQUIRED, UpgradeState.UNCORDON_REQUIRED,
     "pod back on previous revision & ready (was schedulable)"),
    (UpgradeState.ROLLBACK_REQUIRED, UpgradeState.DONE,
     "pod back on previous revision & ready (was cordoned before "
     "upgrade)"),
    (UpgradeState.ROLLBACK_REQUIRED, UpgradeState.FAILED,
     "rollback pod crash-looping (>10 restarts)"),
    (UpgradeState.CORDON_REQUIRED, UpgradeState.ABORT_REQUIRED,
     "capacity collapse | maintenance-window close (abort, don't strand)"),
    (UpgradeState.WAIT_FOR_JOBS_REQUIRED, UpgradeState.ABORT_REQUIRED,
     "capacity collapse | maintenance-window close (abort, don't strand)"),
    (UpgradeState.POD_DELETION_REQUIRED, UpgradeState.ABORT_REQUIRED,
     "capacity collapse | maintenance-window close (abort, don't strand)"),
    (UpgradeState.DRAIN_REQUIRED, UpgradeState.ABORT_REQUIRED,
     "capacity collapse | maintenance-window close (abort, don't strand)"),
    (UpgradeState.ABORT_REQUIRED, UpgradeState.UPGRADE_REQUIRED,
     "eviction halted, serving gate released, uncordoned — zero residue"),
)

#: Adjacency view of STATE_EDGES, keyed by label value ("" = unknown).
LEGAL_EDGES: dict[str, frozenset[str]] = {
    src: frozenset(d.value for s, d, _ in STATE_EDGES if s.value == src)
    for src in {s.value for s, _, _ in STATE_EDGES}
}

#: Upgrade states in which a node must not receive NEW workload pods:
#: from wait-for-jobs onward the node's runtime is being (or about to
#: be) torn down, and the machine guarantees the node is cordoned for
#: that whole window (cordon precedes wait-for-jobs; uncordon follows
#: validation). The chaos harness's InvariantMonitor asserts the
#: guarantee — a workload pod landing on a node in one of these states
#: means a cordon was lost or an uncordon fired early.
WORKLOAD_UNSAFE_STATES = frozenset(str(s) for s in (
    UpgradeState.WAIT_FOR_JOBS_REQUIRED,
    UpgradeState.POD_DELETION_REQUIRED,
    UpgradeState.DRAIN_REQUIRED,
    UpgradeState.POD_RESTART_REQUIRED,
    UpgradeState.VALIDATION_REQUIRED,
    UpgradeState.FAILED,
    UpgradeState.ROLLBACK_REQUIRED,
))

class RemediationState(str, enum.Enum):
    """Per-node states of the UNPLANNED-fault (auto-remediation) machine.

    The planned-upgrade machine (:class:`UpgradeState`) assumes the node
    is healthy and the disruption is chosen; this machine is its dual —
    the disruption already happened (a wedged TPU node: NotReady kubelet,
    crash-looping libtpu pod, stuck-Terminating workload, device-plugin
    health condition) and the operator must claw the node back. Stored
    under a *separate* node label (:class:`RemediationKeys`), so the two
    machines coexist on one node and each reconcile stays stateless and
    idempotent the same way the upgrade labels do
    (upgrade_state.go:68-72).
    """

    # Node healthy (or not yet examined). Absence of the label / empty.
    HEALTHY = ""
    # A wedge signal persisted past its grace window; waiting for a
    # remediation slot (concurrency + availability budget).
    WEDGED = "wedged"
    # Slot granted: the node must be made unschedulable before recovery
    # actions run.
    CORDON_REQUIRED = "cordon-required"
    # Workload pods must be evicted so recovery actions cannot destroy
    # in-flight work invisibly.
    DRAIN_REQUIRED = "drain-required"
    # Cheapest recovery rung: delete the runtime (libtpu) pod so the
    # DaemonSet controller recreates it fresh.
    RESTART_REQUIRED = "runtime-restart-required"
    # Escalation rung: a host reboot has been (or must be) requested via
    # the NodeRebooter seam.
    REBOOT_REQUIRED = "reboot-required"
    # Recovery action completed; the wedge signal must stay clear for the
    # settle window and the validation gate must pass.
    REVALIDATE_REQUIRED = "revalidate-required"
    # Recovered; node must be made schedulable again.
    UNCORDON_REQUIRED = "uncordon-required"
    # Attempt budget exhausted; node stays quarantined for manual repair.
    FAILED = "remediation-failed"
    # Condemned member of a multi-host slice, with topology
    # reconfiguration enabled: the SliceReconfigurer must release the
    # slice (remap it onto a spare, or admit a documented degraded
    # shape) before the node parks back in ``remediation-failed``. The
    # Ironwood-retrospective analogue of optical-circuit-switch
    # reconfiguration: route the slice AROUND the dead host instead of
    # parking the whole ICI domain on its repair.
    RECONFIGURE_REQUIRED = "reconfigure-required"
    # Condemned-at-risk: the FailurePrecursorModel predicts this node is
    # going to die (ECC / link-flap / thermal precursor rates over
    # threshold), so the machine routes around it BEFORE the failure —
    # spare reserved, slice remapped, node drained as a *planned*
    # low-cost candidate — all while the node still serves. The
    # predictive dual of the reactive wedge arc: same reconfigure
    # machinery, entered from a LIVE node instead of a dead one.
    AT_RISK = "at-risk"

    def __str__(self) -> str:  # label values are plain strings
        return self.value


#: Remediation states that consume a concurrency slot — every state in
#: which the machine is actively driving the node. FAILED is excluded:
#: a node parked for manual repair must not starve the rest of the fleet
#: of remediation slots (it still counts as unavailable via its cordon).
#: RECONFIGURE_REQUIRED is excluded for the same reason: the node is
#: already dead and cordoned, and waiting for a spare to provision and
#: upgrade can take a long time — holding a slot for that window would
#: starve live wedges of remediation. AT_RISK is excluded too: the node
#: is still healthy and serving while its replacement provisions, and it
#: is governed by its own fleet-wide condemnation budget
#: (PrecursorPolicySpec.max_at_risk) rather than the remediation
#: concurrency slots — a precursor storm must never crowd out real
#: wedges.
REMEDIATION_IN_PROGRESS_STATES = (
    RemediationState.CORDON_REQUIRED,
    RemediationState.DRAIN_REQUIRED,
    RemediationState.RESTART_REQUIRED,
    RemediationState.REBOOT_REQUIRED,
    RemediationState.REVALIDATE_REQUIRED,
    RemediationState.UNCORDON_REQUIRED,
)

#: Every remediation bucket, in apply_state processing order.
REMEDIATION_ALL_STATES = (
    RemediationState.HEALTHY,
    RemediationState.AT_RISK,
    RemediationState.WEDGED,
    RemediationState.CORDON_REQUIRED,
    RemediationState.DRAIN_REQUIRED,
    RemediationState.RESTART_REQUIRED,
    RemediationState.REBOOT_REQUIRED,
    RemediationState.REVALIDATE_REQUIRED,
    RemediationState.UNCORDON_REQUIRED,
    RemediationState.FAILED,
    RemediationState.RECONFIGURE_REQUIRED,
)

#: Legal transitions of the remediation machine — single source of truth
#: for the graph, exactly like :data:`STATE_EDGES` for upgrades: the e2e
#: suite asserts observed transitions against it and
#: docs/remediation-state-diagram.{dot,svg} are generated from it with a
#: drift-check test (tools/state_diagram.py).
REMEDIATION_EDGES: tuple[
        tuple[RemediationState, RemediationState, str], ...] = (
    (RemediationState.HEALTHY, RemediationState.WEDGED,
     "wedge signal persisted past its grace window"),
    (RemediationState.WEDGED, RemediationState.HEALTHY,
     "signal cleared before any recovery action ran"),
    (RemediationState.WEDGED, RemediationState.CORDON_REQUIRED,
     "slot available (concurrency + availability budget)"),
    (RemediationState.WEDGED, RemediationState.FAILED,
     "attempt budget exhausted"),
    (RemediationState.CORDON_REQUIRED, RemediationState.DRAIN_REQUIRED,
     "cordoned (upgrade flow parked via skip label)"),
    (RemediationState.DRAIN_REQUIRED, RemediationState.RESTART_REQUIRED,
     "workloads evicted; attempt within restart rungs"),
    (RemediationState.DRAIN_REQUIRED, RemediationState.REBOOT_REQUIRED,
     "workloads evicted; restart rungs exhausted, rebooter available"),
    (RemediationState.DRAIN_REQUIRED, RemediationState.FAILED,
     "no recovery action applicable (no pod, no rebooter)"),
    (RemediationState.RESTART_REQUIRED,
     RemediationState.REVALIDATE_REQUIRED,
     "runtime pod deleted and recreated Ready"),
    (RemediationState.RESTART_REQUIRED, RemediationState.WEDGED,
     "restart timeout (attempt consumed)"),
    (RemediationState.REBOOT_REQUIRED,
     RemediationState.REVALIDATE_REQUIRED,
     "reboot completed; node Ready again"),
    (RemediationState.REBOOT_REQUIRED, RemediationState.WEDGED,
     "reboot timeout (attempt consumed)"),
    (RemediationState.REVALIDATE_REQUIRED,
     RemediationState.UNCORDON_REQUIRED,
     "signal clear for settle window + validator passed "
     "(was schedulable)"),
    (RemediationState.REVALIDATE_REQUIRED, RemediationState.HEALTHY,
     "signal clear for settle window + validator passed "
     "(was cordoned before remediation)"),
    (RemediationState.REVALIDATE_REQUIRED, RemediationState.WEDGED,
     "wedge signal returned | revalidation timeout"),
    (RemediationState.UNCORDON_REQUIRED, RemediationState.HEALTHY,
     "uncordoned; bookkeeping cleared"),
    (RemediationState.FAILED, RemediationState.REVALIDATE_REQUIRED,
     "signal cleared out-of-band | manual re-arm annotation"),
    (RemediationState.FAILED, RemediationState.RECONFIGURE_REQUIRED,
     "condemned member of a multi-host slice; reconfiguration enabled"),
    (RemediationState.RECONFIGURE_REQUIRED, RemediationState.FAILED,
     "slice released: remapped onto spare | degraded shape admitted"),
    (RemediationState.RECONFIGURE_REQUIRED,
     RemediationState.REVALIDATE_REQUIRED,
     "manual re-arm during reconfiguration (remap aborted)"),
    (RemediationState.HEALTHY, RemediationState.AT_RISK,
     "precursor verdict held for min_observations; at-risk budget "
     "admitted"),
    (RemediationState.AT_RISK, RemediationState.HEALTHY,
     "precursor risk subsided before the remap joined; booking "
     "dropped"),
    (RemediationState.AT_RISK, RemediationState.WEDGED,
     "hardware beat the planned drain: wedge signal on an at-risk "
     "node (no grace)"),
    (RemediationState.AT_RISK, RemediationState.FAILED,
     "slice released while serving: node drained planned and parked "
     "condemned-at-risk"),
)

#: Adjacency view of REMEDIATION_EDGES, keyed by label value
#: ("" = healthy).
REMEDIATION_LEGAL_EDGES: dict[str, frozenset[str]] = {
    src: frozenset(d.value for s, d, _ in REMEDIATION_EDGES
                   if s.value == src)
    for src in {s.value for s, _, _ in REMEDIATION_EDGES}
}

#: Remediation states in which a node must not receive NEW workload
#: pods: recovery actions (drain/restart/reboot/revalidate) run only on
#: a quarantined node — the machine cordons at admission and uncordons
#: only after revalidation passes. Dual of WORKLOAD_UNSAFE_STATES, used
#: by the chaos InvariantMonitor. AT_RISK is deliberately NOT here: the
#: whole point of condemn-before-fail is that the node keeps serving its
#: slice (schedulable, pods Ready) until the replacement has joined.
REMEDIATION_WORKLOAD_UNSAFE_STATES = frozenset(str(s) for s in (
    RemediationState.DRAIN_REQUIRED,
    RemediationState.RESTART_REQUIRED,
    RemediationState.REBOOT_REQUIRED,
    RemediationState.REVALIDATE_REQUIRED,
    RemediationState.RECONFIGURE_REQUIRED,
))

#: Label key whose presence identifies a TPU node on GKE.
TPU_RESOURCE_NAME = "google.com/tpu"

#: GKE node labels describing TPU slice topology. Used by
#: tpu_operator_libs.topology to derive the upgrade unit (sub-slice).
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"

#: The label kubelet/DaemonSet controller stamps on DS pods with the hash of
#: the ControllerRevision they were created from (pod_manager.go:70-73).
POD_CONTROLLER_REVISION_HASH_LABEL = "controller-revision-hash"

#: Merge-patch value meaning "delete this annotation"
#: (node_upgrade_state_provider.go:147-151).
NULL_STRING = "null"
TRUE_STRING = "true"


@dataclass(frozen=True)
class UpgradeKeys:
    """Instance-scoped builder for the node label/annotation keys.

    One instance per managed accelerator runtime; default is the libtpu
    runtime under the ``google.com`` domain.  A GPU-flavoured instance
    (``UpgradeKeys(driver="gpu", domain="nvidia.com")``) reproduces the
    reference key scheme exactly, which is how mixed GPU+TPU clusters are
    supported (BASELINE config #5).

    Reference: the seven Get*Key() builders in pkg/upgrade/util.go:97-139.
    """

    driver: str = "libtpu"
    domain: str = "google.com"

    @property
    def state_label(self) -> str:
        """Node label carrying the upgrade state (consts.go:20-21)."""
        return f"{self.domain}/{self.driver}-upgrade-state"

    @property
    def skip_label(self) -> str:
        """Node label opting a node out of upgrades (consts.go:22-23)."""
        return f"{self.domain}/{self.driver}-upgrade.skip"

    @property
    def shard_label(self) -> str:
        """Ring-derived shard id stamped on nodes AND runtime pods at
        admission (k8s/sharding.py ShardLabelStamper): the selector key
        server-side watch sharding filters each replica's LIST/WATCH
        with. The value depends only on the ring (name/pool hash), so
        concurrent stampers always write identical values and shard
        handovers never re-stamp — only the watcher's selector moves."""
        return f"{self.domain}/{self.driver}-upgrade.shard"

    @property
    def wait_for_safe_load_annotation(self) -> str:
        """Annotation the runtime init container sets to request a safe
        (cordoned + drained) first load (consts.go:24-27)."""
        return f"{self.domain}/{self.driver}-upgrade.wait-for-safe-load"

    @property
    def initial_state_annotation(self) -> str:
        """Annotation remembering the node was already unschedulable when the
        upgrade started, so it is not uncordoned at the end
        (consts.go:28-30)."""
        return f"{self.domain}/{self.driver}-upgrade.node-initial-state.unschedulable"

    @property
    def pod_completion_start_annotation(self) -> str:
        """Annotation checkpointing the wall-clock start of the
        wait-for-jobs timeout across reconciles (consts.go:31-34)."""
        return f"{self.domain}/{self.driver}-upgrade.wait-for-pod-completion-start-time"

    @property
    def validation_start_annotation(self) -> str:
        """Annotation checkpointing the start of the validation timeout
        (consts.go:35-37)."""
        return f"{self.domain}/{self.driver}-upgrade.validation-start-time"

    @property
    def upgrade_requested_annotation(self) -> str:
        """Annotation requesting an on-demand upgrade (the only trigger for
        orphaned pods, whose revision hash cannot be compared)
        (consts.go:38-41)."""
        return f"{self.domain}/{self.driver}-upgrade-requested"

    @property
    def quarantined_revision_annotation(self) -> str:
        """DAEMONSET annotation recording a revision hash the
        RolloutGuard condemned (canary failure threshold tripped). While
        the DaemonSet's newest ControllerRevision still carries this
        hash the fleet is HALTED: no node newly enters the upgrade flow
        and no runtime pod is restarted onto it. The annotation is the
        durable halt commit — an operator crash between halt and
        rollback resumes from it — and it outlives the rollback as the
        quarantine record, so reconcile never re-attempts the hash until
        the DS spec changes (a changed spec means a different hash)."""
        return f"{self.domain}/{self.driver}-upgrade.quarantined-revision"

    @property
    def canary_passed_annotation(self) -> str:
        """DAEMONSET annotation: ``<revision-hash>:<epoch-seconds>``
        stamped when every canary-cohort node reached upgrade-done on
        that revision. Fleet waves open once the bake time has elapsed
        past the stamp; keyed by hash so a new rollout re-runs its own
        canary instead of inheriting the previous rollout's verdict."""
        return f"{self.domain}/{self.driver}-upgrade.canary-passed"

    @property
    def canary_shard_passed_prefix(self) -> str:
        """DAEMONSET annotation key PREFIX (``<prefix><shard-id>``):
        per-shard canary attestation under the sharded control plane's
        partition-scoped reads. A replica that only holds its own
        partition's pods cannot verify cohort members on other shards,
        so each shard's OWNER stamps ``<revision-hash>`` here once every
        cohort member in that shard is upgrade-done on the revision
        (pod hash verified against its own partition). Distinct keys
        per shard — concurrent owners' merge patches compose (the
        budget-share ledger idiom) — and the fleet-wide
        ``canary_passed_annotation`` is only written once every
        cohort-bearing shard's attestation matches the revision."""
        return f"{self.domain}/{self.driver}-upgrade.canary-shard-passed."

    @property
    def phase_start_annotation(self) -> str:
        """NODE annotation ``<phase>:<epoch-seconds>`` stamping when the
        node entered its current upgrade phase (drain / restart /
        validate — see upgrade/predictor.py). Ridden onto the SAME merge
        patch as the state-label commit, so it is crash-atomic with the
        transition: a restarted operator (or a shard takeover) closes
        the in-flight phase's duration sample from this stamp alone —
        the durable half of online duration learning. Deleted when the
        node leaves the phased flow (done/failed/rollback)."""
        return f"{self.domain}/{self.driver}-upgrade.phase-start"

    @property
    def phase_durations_annotation(self) -> str:
        """NODE annotation ``drain=<s>,restart=<s>,validate=<s>`` of the
        node's most recently observed per-phase durations, updated on
        the same patch that closes each phase. The durable per-node
        model seed: a fresh operator incarnation (or the next shard
        owner after a takeover, or the next ROLLOUT after a crash)
        predicts this node from cluster state alone instead of falling
        back to the fleet pool — so it survives upgrade-done. Benches
        comparing predictive vs flat cells exclude this key (and the
        phase-start stamp) from their final-state fingerprints; it is
        the feature's own durable state, not rollout residue."""
        return f"{self.domain}/{self.driver}-upgrade.phase-durations"

    @property
    def trace_id_annotation(self) -> str:
        """NODE annotation carrying the node's open upgrade-journey
        trace id (obs/tracer.py). Stamped on the transition that opens
        the journey and deleted on the one that closes it, riding the
        SAME merge patch as the state-label commit both times — so a
        restarted operator (or the next shard owner) re-adopts the
        in-flight journey under the SAME trace id from cluster state
        alone, and a closed journey leaves zero residue (the abort
        arc's residue audit stays clean)."""
        return f"{self.domain}/{self.driver}-upgrade.trace-id"

    @property
    def prewarm_reservation_annotation(self) -> str:
        """NODE annotation on a prewarm SPARE:
        ``<incumbent>:<model>:<class>`` — this already-upgraded node is
        reserved to bring a replacement serving replica up before the
        named incumbent's drain is admitted (upgrade/handover.py, the
        PR 6 reserve→join idiom at serving granularity). The RESERVE
        stamp: written first, crash-ordered before the ready stamp, so
        a fresh operator incarnation resumes (or releases) the prewarm
        from cluster state alone."""
        return f"{self.domain}/{self.driver}-upgrade.prewarm-reservation"

    @property
    def prewarm_ready_annotation(self) -> str:
        """NODE annotation on a prewarm spare:
        ``<incumbent>:<epoch-seconds>`` stamped once the replacement
        replica passed readiness. The JOIN stamp: the incumbent's
        eviction is only admitted while its spare carries this, so a
        crash between reserve and ready can never let the sole replica
        drain early. Both prewarm stamps are deleted on ONE merge patch
        when the incumbent finishes (or the reservation is abandoned) —
        zero residue, crash-atomic."""
        return f"{self.domain}/{self.driver}-upgrade.prewarm-ready"

    @property
    def artifact_stamp_prefix(self) -> str:
        """NODE annotation key PREFIX (``<prefix><artifact-name>``):
        the durable per-artifact revision stamp of the multi-artifact
        upgrade DAG (policy/dag.py). ``<value>`` is the revision hash
        the artifact's pod was observed ready at on this node. Stamps
        are written through the state provider in DEPENDENCY order,
        one patch each — an artifact's stamp is only ever written
        after every dependency's stamp is durable — so a crashed
        operator resumes the node's DAG from the stamped prefix alone,
        and the chaos gate's ``dag-order`` invariant can audit the
        ordering from watch events."""
        return f"{self.domain}/{self.driver}-upgrade.artifact."

    @property
    def event_reason(self) -> str:
        """Reason string attached to Kubernetes events (util.go:136-139)."""
        return f"{self.driver.upper()}RuntimeUpgrade"


@dataclass(frozen=True)
class RemediationKeys:
    """Instance-scoped builder for the remediation label/annotation keys.

    Parallel to :class:`UpgradeKeys` but under a distinct label family so
    the planned-upgrade and unplanned-fault machines never collide on a
    node. Exposes the same ``state_label`` / ``event_reason`` attribute
    shape, so :class:`~tpu_operator_libs.upgrade.state_provider.
    NodeUpgradeStateProvider` serves as the durable-commit writer for
    both machines unchanged.
    """

    driver: str = "libtpu"
    domain: str = "google.com"

    @property
    def state_label(self) -> str:
        """Node label carrying the remediation state (the durable store
        of the unplanned-fault machine)."""
        return f"{self.domain}/{self.driver}-remediation-state"

    @property
    def skip_label(self) -> str:
        """Node label opting a node out of auto-remediation."""
        return f"{self.domain}/{self.driver}-remediation.skip"

    @property
    def wedge_since_annotation(self) -> str:
        """Epoch-seconds stamp of when the current wedge signal was first
        observed — the grace window and MTTR both derive from it."""
        return f"{self.domain}/{self.driver}-remediation.wedge-first-seen"

    @property
    def wedge_reason_annotation(self) -> str:
        """Machine-readable reason slug of the confirmed wedge."""
        return f"{self.domain}/{self.driver}-remediation.wedge-reason"

    @property
    def attempt_annotation(self) -> str:
        """Count of recovery attempts dispatched for the current wedge
        (the escalation ladder's durable rung pointer)."""
        return f"{self.domain}/{self.driver}-remediation.attempt"

    @property
    def action_start_annotation(self) -> str:
        """Epoch-seconds stamp of when the in-flight recovery action
        (restart/reboot) was dispatched; drives action timeouts."""
        return f"{self.domain}/{self.driver}-remediation.action-start"

    @property
    def restart_pod_uid_annotation(self) -> str:
        """UID of the runtime pod deleted by the restart rung, so 'the
        pod was recreated' is detectable across operator restarts."""
        return f"{self.domain}/{self.driver}-remediation.restart-pod-uid"

    @property
    def settle_start_annotation(self) -> str:
        """Epoch-seconds stamp of when the wedge signal was last observed
        clear during revalidation (the stability window)."""
        return f"{self.domain}/{self.driver}-remediation.settle-start"

    @property
    def reboot_requested_annotation(self) -> str:
        """Epoch-seconds stamp written when a reboot was requested — the
        handshake contract a privileged host agent acts on."""
        return f"{self.domain}/{self.driver}-remediation.reboot-requested-at"

    @property
    def initial_state_annotation(self) -> str:
        """Annotation remembering the node was already unschedulable when
        remediation began, so it is not uncordoned at the end (same
        semantics as the upgrade machine's, consts.go:28-30)."""
        return (f"{self.domain}/{self.driver}"
                f"-remediation.node-initial-state.unschedulable")

    @property
    def rearm_annotation(self) -> str:
        """Annotation an operator sets to re-arm a remediation-failed
        node after manual repair."""
        return f"{self.domain}/{self.driver}-remediation-requested"

    @property
    def condemned_annotation(self) -> str:
        """Epoch-seconds stamp written when the machine gave the node up
        (attempt budget exhausted with the wedge signal still present).
        The durable give-up record: the SliceReconfigurer keys slice
        remaps on it, time-to-remapped is measured from it, and
        operators watching ``kubectl get events`` get the paired
        ``NodeCondemned`` Event instead of a silent FAILED dead end.
        Cleared only when the node recovers."""
        return f"{self.domain}/{self.driver}-remediation.condemned-at"

    @property
    def at_risk_annotation(self) -> str:
        """Epoch-seconds stamp written when the FailurePrecursorModel
        condemned the node AT RISK (predicted failure, node still
        serving). The predictive sibling of ``condemned_annotation``:
        it rides the SAME merge patch as the ``at-risk`` state commit
        (crash-atomic), counts against the fleet-wide at-risk budget,
        and is the MTTR anchor for a condemn-before-fail remap — the
        clock starts at the verdict, not at a death that may never be
        observed. Cleared only when the arc aborts back to healthy."""
        return f"{self.domain}/{self.driver}-remediation.at-risk-at"

    @property
    def at_risk_reason_annotation(self) -> str:
        """Which precursor signal condemned the node (the
        ``PrecursorVerdict.reason`` slug, e.g. ``precursor-ecc:...``) —
        stamped beside ``at_risk_annotation`` so a human reading the
        node object sees the evidence, not just the verdict."""
        return f"{self.domain}/{self.driver}-remediation.at-risk-reason"

    @property
    def precursor_rates_annotation(self) -> str:
        """Durable per-node seed of the FailurePrecursorModel (encoded
        per-signal EWMA rates). Deliberately under a ``-precursor``
        prefix, NOT ``-remediation.``: the seed lives on HEALTHY nodes
        permanently (a fresh incarnation resumes the model from cluster
        state alone), so it must sit outside the remediation-residue
        namespace that the chaos final_check and the reconcile
        fingerprint treat as in-flight arc state."""
        return f"{self.domain}/{self.driver}-precursor.rates"

    @property
    def event_reason(self) -> str:
        """Reason string attached to Kubernetes events."""
        return f"{self.driver.upper()}NodeRemediation"


@dataclass(frozen=True)
class TopologyKeys:
    """Instance-scoped builder for the slice-reconfiguration keys.

    Third key family next to :class:`UpgradeKeys` /
    :class:`RemediationKeys`, same driver/domain scoping. The spare-pool
    label marks hot-standby hosts the
    :class:`~tpu_operator_libs.topology.reconfigurer.SliceReconfigurer`
    may swap into a slice in place of a condemned node; the annotations
    are the remap flow's durable commit points (reservation → join →
    release), so a crashed operator resumes a half-finished remap from
    cluster state alone. The degraded-slices record lives on the runtime
    DaemonSet (one crash-atomic annotation patch — the RolloutGuard
    quarantine idiom) because slices themselves are not API objects.
    """

    driver: str = "libtpu"
    domain: str = "google.com"

    @property
    def spare_pool_label(self) -> str:
        """Node label marking a hot-standby host (value "true"). Spares
        carry the accelerator/topology labels of the slices they can
        replace into, but NO nodepool label — each spare is its own
        single-node "slice" until a remap joins it to a pool."""
        return f"{self.domain}/{self.driver}-topology.spare"

    @property
    def reserved_for_annotation(self) -> str:
        """On a spare: ``<slice-id>/<missing-host>:<epoch>`` — reserved
        to replace ``missing-host`` in ``slice-id`` (stamped at
        reservation time, driving the spare-provision deadline). The
        durable booking that keeps two remaps from claiming one spare,
        and the joint-planning marker the upgrade planners prioritize
        (the spare must reach the target revision while still OUT of the
        slice — one cordon/drain cycle total)."""
        return f"{self.domain}/{self.driver}-topology.reserved-for"

    @property
    def remapped_at_annotation(self) -> str:
        """On a just-joined spare: ``<epoch>:<missing-host>`` stamped in
        the same patch that joins it to the pool. Holds the multislice
        sticky-down membership (the job's replacement pods are still
        Pending right after a remap) until the settle window passes, and
        records which condemned host this join replaced (the crash-safe
        resume marker for the join→release window)."""
        return f"{self.domain}/{self.driver}-topology.remapped-at"

    @property
    def released_from_annotation(self) -> str:
        """On a released condemned node: the slice id it was removed
        from (audit trail; the node itself keeps its condemned
        annotation and stays parked for repair)."""
        return f"{self.domain}/{self.driver}-topology.released-from"

    @property
    def degraded_slices_annotation(self) -> str:
        """DAEMONSET annotation recording admitted degraded shapes:
        ``slice:lost-host[+lost-host...]`` entries, comma-separated,
        sorted (see topology.slice_topology.encode_degraded_slices).
        Written in ONE patch before the condemned node is released, so
        planners and the serving gate always see a truthful capacity
        picture — a slice is never silently short. Entries are removed
        when a late spare heals the slice back to full shape."""
        return f"{self.domain}/{self.driver}-topology.degraded-slices"

    @property
    def event_reason(self) -> str:
        """Reason string attached to Kubernetes events."""
        return f"{self.driver.upper()}SliceReconfiguration"


@dataclass(frozen=True)
class FederationKeys:
    """Instance-scoped builder for the multi-cluster federation keys.

    Fourth key family next to :class:`UpgradeKeys` /
    :class:`RemediationKeys` / :class:`TopologyKeys`, same
    driver/domain scoping. Every durable fact the federation controller
    relies on lives as an annotation on a REGION's runtime DaemonSet —
    inside the region's own cluster, on the same object the region
    operator already reads every pass — so a partitioned or restarted
    regional controller re-derives the federation's verdicts (its
    budget share, the fleet quarantine) from local cluster state alone,
    and a restarted federation controller re-derives the rollout's
    progress by reading the regions back.
    """

    driver: str = "libtpu"
    domain: str = "google.com"

    @property
    def budget_share_annotation(self) -> str:
        """REGION DaemonSet annotation: this region's durable share of
        the GLOBAL disruption budget (an int node count). The region
        operator's effective ``maxUnavailable`` IS this stamp — absent
        or 0 means the region admits nothing — so the federation's
        spend rule is enforced region-locally even while the region is
        partitioned from the federation layer. The ledger invariant
        (the sum of all stamped shares never exceeds the global B) is
        maintained write-side: decreases are stamped immediately,
        increases only while every region's stamp was freshly read
        back this pass (see federation/ledger.py)."""
        return f"{self.domain}/{self.driver}-fed.budget-share"

    @property
    def bake_passed_annotation(self) -> str:
        """CANARY-REGION DaemonSet annotation:
        ``<revision-hash>:<epoch-seconds>`` stamped when the canary
        region reached upgrade-done on the revision (every node DONE,
        every runtime pod on the hash and Ready). Fleet waves open only
        once ``bakeSeconds`` have elapsed past the stamp; keyed by hash
        so a new rollout re-runs its own region bake. The durable half
        of canary-containment: a restarted federation controller may
        not admit any non-canary region without re-reading this stamp
        fresh."""
        return f"{self.domain}/{self.driver}-fed.bake-passed"

    @property
    def probe_annotation(self) -> str:
        """REGION DaemonSet annotation the federation controller
        writes every pass with its current timestamp. Partition
        detection: a region whose probe write is rejected (or never
        read back) is treated as unreachable — its stale reads are
        distrusted, and no budget share anywhere in the fleet may be
        RAISED until every region reads fresh again (decreases stay
        allowed; they only tighten the global inequality)."""
        return f"{self.domain}/{self.driver}-fed.probe"

    @property
    def preshift_reservation_annotation(self) -> str:
        """RESERVE-REGION DaemonSet annotation:
        ``<source-region>:<revision-hash>:<slots>:<epoch>`` — the
        federation's durable claim of session capacity in this region
        on behalf of ``source-region`` before that region is admitted
        to ``revision-hash``. The PrewarmCoordinator reserve→ready
        commit #1 lifted to region granularity: written BEFORE warmup
        starts so a crash between reservation and readiness leaves a
        findable claim, never an orphaned warm pool. ``slots`` is the
        interactive-session count the reserve must absorb. Released
        (with the ready stamp, in ONE patch) once the source region's
        rollout quiesced — zero residue is an fsck-audited invariant."""
        return f"{self.domain}/{self.driver}-fed.preshift-reservation"

    @property
    def preshift_ready_annotation(self) -> str:
        """RESERVE-REGION DaemonSet annotation:
        ``<source-region>:<revision-hash>:<epoch>`` — commit #2 of the
        region-level pre-shift pair: the reserve capacity passed its
        readiness probe and the source region's interactive sessions
        may be routed here. The source region is admitted only after
        this stamp exists (reserve→ready→admit ordering), so a region
        admission never races its own traffic off a cliff. Ready
        implies reservation; a ready stamp without its reservation is
        a torn write the auditor flags. Both stamps are deleted in the
        same merge patch on release (crash-atomic, zero residue)."""
        return f"{self.domain}/{self.driver}-fed.preshift-ready"

    @property
    def event_reason(self) -> str:
        """Reason string attached to Kubernetes events."""
        return f"{self.driver.upper()}FederatedRollout"


#: Field selector template filtering pods by the node they run on
#: (consts.go:70-73).
NODE_NAME_FIELD_SELECTOR_FMT = "spec.nodeName={}"
