"""Shared online estimators: EWMA updates + pooled bucketed histograms.

Extracted from the PR 9 duration predictor so every online model in the
operator — :class:`~tpu_operator_libs.upgrade.predictor.
PhaseDurationPredictor` (per-node phase durations) and
:class:`~tpu_operator_libs.health.precursor.FailurePrecursorModel`
(per-node hardware-counter rates) — runs the SAME arithmetic instead of
a copy-paste second implementation. Both models share the shape the
cost-aware-duration paper (PAPERS.md) argues for: a per-entity EWMA as
the warm path, with a fleet-pooled bucketed histogram as the cold-start
fallback (bounded memory at 100k nodes — no sample lists; quantiles via
the shared ``metrics.quantile_from_buckets`` estimator).
"""

from __future__ import annotations

from typing import Iterable, Optional

from tpu_operator_libs.metrics import quantile_from_buckets


def ewma_update(previous: Optional[float], sample: float,
                smoothing: float) -> float:
    """One exponentially-weighted-moving-average step.

    ``a * sample + (1 - a) * previous``; seeds to the raw sample when no
    previous value exists (the first observation IS the model).
    """
    if previous is None:
        return sample
    return smoothing * sample + (1.0 - smoothing) * previous


class PooledHistogram:
    """Bucketed sample histogram with bounded memory.

    Cumulative ``le`` bucket counts (Prometheus-histogram shape) plus a
    running count/total, so the pool costs O(buckets) regardless of
    fleet size. Quantiles interpolate within the winning bucket via
    ``metrics.quantile_from_buckets``. NOT thread-safe by itself — the
    owning model serializes mutations under its own coarse lock, exactly
    where the rest of its state is guarded.
    """

    __slots__ = ("buckets", "counts", "count", "total")

    def __init__(self, buckets: Iterable[float]) -> None:
        self.buckets = tuple(buckets)
        if not self.buckets:
            raise ValueError("PooledHistogram needs at least one bucket")
        self.counts = [0] * len(self.buckets)
        self.count = 0
        self.total = 0.0

    def record(self, value: float) -> None:
        for i, le in enumerate(self.buckets):
            if value <= le:
                self.counts[i] += 1
        self.count += 1
        self.total += value

    def quantile(self, q: float) -> Optional[float]:
        return quantile_from_buckets(self.buckets, self.counts,
                                     self.count, q)

    def confidence_interval(self, q: float) -> "Optional[tuple[float, float]]":
        """Central ``q``-interval ``(lower, upper)`` of the pooled
        samples — the quantile pair ``((1-q)/2, (1+q)/2)``. None while
        the pool is empty (callers choose their own cold-start spread
        rather than inheriting a fabricated one)."""
        if not 0.0 < q < 1.0:
            raise ValueError("q must be in (0, 1)")
        if not self.count:
            return None
        lower = self.quantile((1.0 - q) / 2.0)
        upper = self.quantile((1.0 + q) / 2.0)
        if lower is None or upper is None:
            return None
        return lower, upper

    @property
    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None
