"""Recording mock managers for state-machine-isolation tests.

Equivalent of the reference's mockery-generated testify mocks
(pkg/upgrade/mocks/): drop-in implementations of every manager seam on
ClusterUpgradeStateManager that record calls and apply the observable side
effect in memory (e.g. the mocked state provider just mutates the node's
label, mirroring upgrade_suit_test.go:100-105), so transition logic can be
tested without any cluster at all.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from tpu_operator_libs.consts import NULL_STRING, UpgradeKeys, UpgradeState
from tpu_operator_libs.k8s.objects import DaemonSet, Node, Pod
from tpu_operator_libs.upgrade.drain_manager import DrainConfiguration
from tpu_operator_libs.upgrade.pod_manager import PodManagerConfig


@dataclass
class Call:
    method: str
    args: tuple

    def __repr__(self) -> str:
        return f"{self.method}{self.args!r}"


class RecordingMixin:
    def __init__(self) -> None:
        self.calls: list[Call] = []

    def record(self, method: str, *args: object) -> None:
        self.calls.append(Call(method, args))

    def calls_to(self, method: str) -> list[Call]:
        return [c for c in self.calls if c.method == method]


class MockNodeUpgradeStateProvider(RecordingMixin):
    """Mutates node labels/annotations in memory (no cluster, no polling).

    Models the real provider's optimistic-concurrency contract: each
    node's last committed label is tracked in ``live_states``, and a
    write whose snapshot label disagrees with it is skipped with
    ``False`` — so mock-driven tests can exercise the stale-snapshot
    path (seed ``live_states`` to simulate a concurrent pass).
    """

    def __init__(self, keys: Optional[UpgradeKeys] = None) -> None:
        super().__init__()
        self.keys = keys or UpgradeKeys()
        self.fail_next: Optional[Exception] = None
        self.live_states: dict[str, str] = {}
        self.fence = None

    def with_fence(self, fence: "object") -> "MockNodeUpgradeStateProvider":
        """Sharded-control-plane seam parity: install the (node_name,
        nodepool) fence the real provider checks before every durable
        write. The mock stores it so with_sharding-driven tests can
        assert the installation; mock writes do not call it (there is
        no wire to fence)."""
        self.fence = fence
        return self

    def _maybe_fail(self) -> None:
        if self.fail_next is not None:
            exc, self.fail_next = self.fail_next, None
            raise exc

    def get_node(self, name: str) -> Node:
        raise NotImplementedError(
            "MockNodeUpgradeStateProvider has no store; tests build "
            "snapshots directly")

    def change_node_upgrade_state(
            self, node: Node, new_state: UpgradeState | str,
            annotations: "Optional[dict[str, Optional[str]]]" = None,
    ) -> bool:
        self.record("change_node_upgrade_state", node.metadata.name,
                    str(new_state))
        self._maybe_fail()
        value = str(new_state)
        name = node.metadata.name
        expected = node.metadata.labels.get(self.keys.state_label, "")
        current = self.live_states.get(name, expected)
        if current not in (expected, value):
            return False  # stale snapshot, same as the real provider
        self.live_states[name] = value
        node.metadata.labels[self.keys.state_label] = value
        # coalesced annotations commit with the label, like the real
        # provider's single merge patch
        for key, ann_value in (annotations or {}).items():
            if ann_value is None or ann_value == NULL_STRING:
                node.metadata.annotations.pop(key, None)
            else:
                node.metadata.annotations[key] = ann_value
        return True

    def change_node_upgrade_annotation(self, node: Node, key: str,
                                       value: Optional[str]) -> None:
        self.record("change_node_upgrade_annotation", node.metadata.name,
                    key, value)
        self._maybe_fail()
        if value is None or value == NULL_STRING:
            node.metadata.annotations.pop(key, None)
        else:
            node.metadata.annotations[key] = value

    def change_node_upgrade_annotations(
            self, node: Node,
            annotations: dict[str, Optional[str]]) -> None:
        self.record("change_node_upgrade_annotations", node.metadata.name,
                    dict(annotations))
        self._maybe_fail()
        for key, value in annotations.items():
            if value is None or value == NULL_STRING:
                node.metadata.annotations.pop(key, None)
            else:
                node.metadata.annotations[key] = value


class MockCordonManager(RecordingMixin):
    def __init__(self) -> None:
        super().__init__()
        self.fail_next: Optional[Exception] = None
        self.fence = None

    def with_fence(self, fence: "object") -> "MockCordonManager":
        """Sharded-control-plane seam parity (see the provider mock)."""
        self.fence = fence
        return self

    def cordon(self, node: Node) -> None:
        self.record("cordon", node.metadata.name)
        if self.fail_next is not None:
            exc, self.fail_next = self.fail_next, None
            raise exc
        node.spec.unschedulable = True

    def uncordon(self, node: Node) -> None:
        self.record("uncordon", node.metadata.name)
        if self.fail_next is not None:
            exc, self.fail_next = self.fail_next, None
            raise exc
        node.spec.unschedulable = False


class MockDrainManager(RecordingMixin):
    #: readable surface parity with the real manager (property there)
    eviction_gate = None

    def __init__(self) -> None:
        super().__init__()
        self.fail_next: Optional[Exception] = None

    def schedule_nodes_drain(self, config: DrainConfiguration) -> None:
        self.record("schedule_nodes_drain",
                    tuple(n.metadata.name for n in config.nodes))
        if self.fail_next is not None:
            exc, self.fail_next = self.fail_next, None
            raise exc

    def release_gate(self, node: Node, pods: "list[Pod]") -> None:
        """Mid-flight abort seam (process_abort_required_nodes)."""
        self.record("release_gate", node.metadata.name)

    def join(self, timeout: float = 0.0) -> None:
        pass


class MockPodManager(RecordingMixin):
    """Revision hashes come from an in-memory dict (default: everything in
    sync with hash 'test-hash-12345', upgrade_suit_test.go:144-156)."""

    #: readable surface parity with the real manager (properties there;
    #: state_manager reads pod_manager.eviction_gate when re-building
    #: the manager for pod-deletion mode)
    eviction_gate = None
    deletion_filter = None

    def __init__(self) -> None:
        super().__init__()
        self.pod_hashes: dict[str, str] = {}
        self.ds_hashes: dict[str, str] = {}
        self.previous_hashes: dict[str, str] = {}
        self.default_hash = "test-hash-12345"

    def get_pod_revision_hash(self, pod: Pod) -> str:
        self.record("get_pod_revision_hash", pod.name)
        return self.pod_hashes.get(pod.name, self.default_hash)

    def get_daemon_set_revision_hash(self, ds: DaemonSet) -> str:
        self.record("get_daemon_set_revision_hash", ds.name)
        return self.ds_hashes.get(ds.name, self.default_hash)

    def get_previous_daemon_set_revision_hash(
            self, ds: DaemonSet) -> Optional[str]:
        self.record("get_previous_daemon_set_revision_hash", ds.name)
        return self.previous_hashes.get(ds.name)

    def release_gate(self, node: Node, pods: "list[Pod]") -> None:
        """Mid-flight abort seam (process_abort_required_nodes)."""
        self.record("release_gate", node.metadata.name)

    def reset_revision_cache(self) -> None:
        # deliberately not recorded: it is per-pass bookkeeping, and
        # recording it would pollute call-sequence assertions
        pass

    def schedule_pod_eviction(self, config: PodManagerConfig) -> None:
        self.record("schedule_pod_eviction",
                    tuple(n.metadata.name for n in config.nodes))

    def schedule_pods_restart(self, pods: list[Pod]) -> int:
        self.record("schedule_pods_restart", tuple(p.name for p in pods))
        return 0  # same contract as the real manager: deferred count

    def schedule_check_on_pod_completion(
            self, config: PodManagerConfig) -> None:
        self.record("schedule_check_on_pod_completion",
                    tuple(n.metadata.name for n in config.nodes))

    def is_pod_running_or_pending(self, pod: Pod) -> bool:
        """Full-interface parity with the real manager (the reference's
        generated mock covers IsPodRunningOrPending the same way).
        Delegates to the real static predicate — duplicating the phase
        set here could silently drift from it."""
        self.record("is_pod_running_or_pending", pod.name)
        from tpu_operator_libs.upgrade.pod_manager import PodManager

        return PodManager.is_pod_running_or_pending(pod)

    def handle_timeout_on_pod_completions(self, node: Node,
                                          timeout_seconds: int) -> None:
        self.record("handle_timeout_on_pod_completions",
                    node.metadata.name, timeout_seconds)

    def join(self, timeout: float = 0.0) -> None:
        pass


class MockValidationManager(RecordingMixin):
    #: readable surface parity with the real manager (property there)
    pod_selector = ""

    def __init__(self, result: bool = True) -> None:
        super().__init__()
        self.result = result

    def validate(self, node: Node) -> bool:
        self.record("validate", node.metadata.name)
        return self.result

    def check(self, node: Node) -> bool:
        self.record("check", node.metadata.name)
        return self.result


class MockSafeLoadManager(RecordingMixin):
    def __init__(self, keys: Optional[UpgradeKeys] = None) -> None:
        super().__init__()
        self.keys = keys or UpgradeKeys()

    def is_waiting_for_safe_load(self, node: Node) -> bool:
        self.record("is_waiting_for_safe_load", node.metadata.name)
        return bool(node.metadata.annotations.get(
            self.keys.wait_for_safe_load_annotation))

    def unblock_loading(self, node: Node) -> None:
        self.record("unblock_loading", node.metadata.name)
        node.metadata.annotations.pop(
            self.keys.wait_for_safe_load_annotation, None)


def mock_managers(keys: Optional[UpgradeKeys] = None) -> dict:
    """Kwargs bundle: ClusterUpgradeStateManager(client, keys,
    **mock_managers()) wires every seam to a mock (the reference swaps the
    fields the same way, upgrade_state_test.go:48-56)."""
    keys = keys or UpgradeKeys()
    return {
        "provider": MockNodeUpgradeStateProvider(keys),
        "cordon_manager": MockCordonManager(),
        "drain_manager": MockDrainManager(),
        "pod_manager": MockPodManager(),
        "validation_manager": MockValidationManager(),
        "safe_load_manager": MockSafeLoadManager(keys),
    }
