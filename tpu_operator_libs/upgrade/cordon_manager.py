"""Cordon / uncordon nodes (reference cordon_manager.go:25-56)."""

from __future__ import annotations

import logging
from typing import Callable, Optional

from tpu_operator_libs.consts import GKE_NODEPOOL_LABEL
from tpu_operator_libs.k8s.client import K8sClient
from tpu_operator_libs.k8s.drain import run_cordon_or_uncordon
from tpu_operator_libs.k8s.objects import Node

logger = logging.getLogger(__name__)


class CordonManager:
    """Marks nodes (un)schedulable via the drain helper's cordon path.

    ``fence`` is the sharded-control-plane split-brain gate (the same
    ``(node_name, nodepool)`` contract as the state provider's): a
    cordon/uncordon is a durable node write too, so a deposed replica
    must not flip schedulability outside its partition either.
    """

    def __init__(self, client: K8sClient,
                 fence: Optional[Callable[[str, str], None]] = None,
                 ) -> None:
        self._client = client
        self._fence = fence

    def with_fence(self, fence: Optional[Callable[[str, str], None]],
                   ) -> "CordonManager":
        self._fence = fence
        return self

    def _check_fence(self, node: Node) -> None:
        if self._fence is not None:
            self._fence(node.metadata.name,
                        node.metadata.labels.get(GKE_NODEPOOL_LABEL, ""))

    def cordon(self, node: Node) -> None:
        self._check_fence(node)
        run_cordon_or_uncordon(self._client, node.metadata.name, True)
        node.spec.unschedulable = True
        logger.info("cordoned node %s", node.metadata.name)

    def uncordon(self, node: Node) -> None:
        self._check_fence(node)
        run_cordon_or_uncordon(self._client, node.metadata.name, False)
        node.spec.unschedulable = False
        logger.info("uncordoned node %s", node.metadata.name)
