"""ReconcileNudger: completion-driven wakeups + the deadline timer wheel.

The reference's async drain design (drain_manager.go:58-138) commits
worker outcomes as node labels that the reconcile loop only discovers on
its next poll, and every timeout in the system (canary bake, validation,
wait-for-jobs, retry backoff) expires silently between resyncs. At fleet
scale that idle time — not the pass cost PR 3 already drove to O(delta)
— dominates upgrade makespan: every async hop pays up to a full resync
interval of dead air.

This module is the seam that removes it. A single
:class:`ReconcileNudger` instance is threaded through the state
machines and their node-action managers; anything that learns an async
outcome calls :meth:`ReconcileNudger.nudge` the instant the outcome
lands, and anything that stamps a future deadline registers it on the
:class:`DeadlineTimerWheel` via :meth:`ReconcileNudger.nudge_at` /
:meth:`ReconcileNudger.nudge_after`.

Two consumption modes, one object:

- **Live (bound)** — :meth:`ReconcileNudger.bind` wires the nudger to a
  running :class:`~tpu_operator_libs.controller.Controller`:
  ``nudge`` enqueues the cluster key immediately and deadline slots are
  scheduled through ``WorkQueue.add_after``. The work queue's
  three-set dedup guarantees a burst of nudges coalesces into at most
  one queued reconcile (no double reconcile for one event), and the
  wheel's slotting guarantees near-simultaneous deadlines cost one
  wakeup, not one each.
- **Driven (unbound)** — simulation/bench/chaos harnesses own the clock
  and the loop; they poll :meth:`consume_pending` and
  :meth:`next_deadline` to decide when the next reconcile runs. Nothing
  is lost while unbound: a later ``bind`` flushes the pending nudge and
  re-schedules every outstanding deadline slot.

Every wakeup request is counted by source (``drain``, ``eviction``,
``validation-timeout``, ``canary-bake``, …) — the evidence feed for
``metrics.observe_latency`` and ``cluster_status``.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from tpu_operator_libs.util import Clock


class DeadlineTimerWheel:
    """Slotted one-shot timer wheel with wakeup coalescing.

    Deadlines are rounded UP to the next ``resolution`` boundary; one
    wakeup is scheduled per occupied slot, so N deadlines landing within
    one slot cost one reconcile instead of N, and no deadline is woken
    early (expiry checks would find nothing to do) — at most
    ``resolution`` seconds late, which the registrants tolerate by
    construction (their stamps are second-granular).

    ``schedule`` is the delay-seconds sink — in live mode a closure over
    the controller's ``WorkQueue.add_after``; ``None`` leaves the wheel
    passive for clock-owning drivers that poll :meth:`next_deadline` /
    :meth:`pop_due` instead.
    """

    def __init__(self, clock: Optional[Clock] = None,
                 schedule: Optional[Callable[[float], None]] = None,
                 resolution: float = 1.0) -> None:
        if resolution <= 0:
            raise ValueError("resolution must be positive")
        self._clock = clock or Clock()
        self._schedule = schedule
        self.resolution = resolution
        self._lock = threading.Lock()
        # occupied slot boundaries (absolute clock seconds)
        self._slots: set[float] = set()
        #: deadlines registered (fresh slots scheduled).
        self.registered_total = 0
        #: deadlines absorbed into an already-scheduled slot.
        self.coalesced_total = 0

    def _slot_of(self, when: float) -> float:
        # ceil to the next boundary; a deadline exactly on a boundary
        # keeps it (never wake early)
        slots = -(-when // self.resolution)
        return slots * self.resolution

    def register(self, when: float) -> bool:
        """Register an absolute-deadline wakeup. Returns True when a new
        slot was scheduled, False when an existing slot already covers
        it (coalesced)."""
        slot = self._slot_of(when)
        now = self._clock.now()
        with self._lock:
            if slot in self._slots:
                self.coalesced_total += 1
                return False
            self._slots.add(slot)
            self.registered_total += 1
            schedule = self._schedule
        if schedule is not None:
            schedule(max(0.0, slot - now))
        return True

    def rebind(self, schedule: Optional[Callable[[float], None]]) -> None:
        """Swap the scheduling sink; outstanding future slots are
        re-scheduled through the new one so nothing registered while
        unbound is lost."""
        now = self._clock.now()
        with self._lock:
            self._schedule = schedule
            pending = sorted(s for s in self._slots if s > now)
        if schedule is not None:
            for slot in pending:
                schedule(max(0.0, slot - now))

    def next_deadline(self) -> Optional[float]:
        """Earliest outstanding slot boundary (absolute seconds), or
        None. Clock-owning drivers advance virtual time to this."""
        with self._lock:
            return min(self._slots) if self._slots else None

    def pop_due(self, now: Optional[float] = None) -> "list[float]":
        """Drop every slot at or before ``now``; returns their times
        (sorted). Live mode relies on ``WorkQueue.add_after`` for the
        actual wakeup and calls this from the nudger to keep the slot
        set (and ``next_deadline``) from growing stale; clock-owning
        drivers use the returned instants for idle-time accounting."""
        if now is None:
            now = self._clock.now()
        with self._lock:
            due = sorted(s for s in self._slots if s <= now)
            for slot in due:
                self._slots.discard(slot)
            return due

    def outstanding(self) -> int:
        with self._lock:
            return len(self._slots)


class ReconcileNudger:
    """The completion-wakeup seam threaded through the state machines.

    Construct once per operator (share between the upgrade and
    remediation machines — they feed the same controller key), hand it
    to the managers, and either :meth:`bind` it to a live controller or
    poll it from a clock-owning driver loop.
    """

    def __init__(self, clock: Optional[Clock] = None,
                 resolution: float = 1.0) -> None:
        self._clock = clock or Clock()
        self._lock = threading.Lock()
        self._wake: Optional[Callable[[], None]] = None
        self.wheel = DeadlineTimerWheel(clock=self._clock,
                                        resolution=resolution)
        self._pending = False
        #: wakeup requests by source label (immediate + deadline).
        self.wakeups_by_source: dict[str, int] = {}
        #: immediate nudges absorbed by an already-pending wakeup.
        self.nudges_coalesced_total = 0

    # ------------------------------------------------------------------
    # producer surface (managers)
    # ------------------------------------------------------------------
    def _count(self, source: str) -> None:
        self.wakeups_by_source[source] = \
            self.wakeups_by_source.get(source, 0) + 1

    def nudge(self, source: str = "completion") -> None:
        """An async outcome just landed: wake the controller now. In
        live mode the work queue dedups bursts; while unbound the
        pending flag does (the driver runs ONE pass per batch)."""
        with self._lock:
            self._count(source)
            if self._pending:
                self.nudges_coalesced_total += 1
            self._pending = True
            wake = self._wake
        if wake is not None:
            wake()

    def nudge_at(self, when: float, source: str = "deadline") -> bool:
        """Register a precise wakeup for an absolute deadline (canary
        bake expiry, validation/wait-for-jobs timeout, backoff retry).
        Returns False when an already-registered slot covers it."""
        with self._lock:
            self._count(source)
        return self.wheel.register(when)

    def nudge_after(self, delay: float, source: str = "deadline") -> bool:
        """Relative-delay form of :meth:`nudge_at`."""
        return self.nudge_at(self._clock.now() + max(0.0, delay), source)

    # ------------------------------------------------------------------
    # live wiring
    # ------------------------------------------------------------------
    def bind(self, wake: Callable[[], None],
             schedule: Optional[Callable[[float], None]] = None) -> None:
        """Wire to a live controller: ``wake`` enqueues an immediate
        reconcile (``Controller.enqueue``); ``schedule`` is the delayed
        form (``lambda d: controller.queue.add_after(CLUSTER_KEY, d)``).
        A nudge that arrived while unbound fires immediately, and every
        outstanding deadline slot is re-scheduled."""
        with self._lock:
            self._wake = wake
            flush = self._pending
            self._pending = False
        self.wheel.rebind(schedule)
        if flush:
            wake()

    def unbind(self) -> None:
        with self._lock:
            self._wake = None
        self.wheel.rebind(None)

    # ------------------------------------------------------------------
    # driver surface (sim/bench/chaos loops that own the clock)
    # ------------------------------------------------------------------
    def consume_pending(self) -> bool:
        """True when an immediate nudge arrived since the last call (the
        driver should reconcile now); clears the flag."""
        with self._lock:
            pending, self._pending = self._pending, False
            return pending

    def next_deadline(self) -> Optional[float]:
        return self.wheel.next_deadline()

    def pop_due(self, now: Optional[float] = None) -> "list[float]":
        """Consume deadline slots due at ``now`` (their times returned).
        Live consumers call this at the top of a reconcile so the slot
        set tracks the queue's delayed items; drivers call it after
        advancing virtual time."""
        return self.wheel.pop_due(now)

    # ------------------------------------------------------------------
    # metrics feed
    # ------------------------------------------------------------------
    def counts_snapshot(self) -> dict[str, int]:
        """Per-source wakeup counts (copy), for status/metrics."""
        with self._lock:
            return dict(sorted(self.wakeups_by_source.items()))
