"""The upgrade state machine and its managers.

Layer map (SURVEY.md §1): this package is L2 (node-action managers) + L3
(cluster state machine). Every manager is an injectable seam on the state
manager, preserving the reference's pluggability-by-interface design
(upgrade_state.go:110-115).
"""

from tpu_operator_libs.upgrade.state_provider import (  # noqa: F401
    NodeUpgradeStateProvider,
)
from tpu_operator_libs.upgrade.cordon_manager import CordonManager  # noqa: F401
from tpu_operator_libs.upgrade.drain_manager import (  # noqa: F401
    DrainConfiguration,
    DrainManager,
)
from tpu_operator_libs.upgrade.pod_manager import (  # noqa: F401
    PodDeletionFilter,
    PodManager,
    PodManagerConfig,
)
from tpu_operator_libs.upgrade.gate import (  # noqa: F401
    EvictionGate,
    GateKeeper,
)
from tpu_operator_libs.upgrade.validation_manager import (  # noqa: F401
    ValidationManager,
)
from tpu_operator_libs.upgrade.safe_load_manager import (  # noqa: F401
    SafeRuntimeLoadManager,
)
from tpu_operator_libs.upgrade.rollout_guard import (  # noqa: F401
    RolloutDecision,
    RolloutGuard,
)
from tpu_operator_libs.upgrade.predictor import (  # noqa: F401
    PhaseDurationPredictor,
    PredictiveWavePlanner,
)
from tpu_operator_libs.upgrade.state_manager import (  # noqa: F401
    BuildStateError,
    ClusterUpgradeState,
    ClusterUpgradeStateManager,
    FlatPlanner,
    NodeUpgradeState,
    UpgradePlanner,
)
