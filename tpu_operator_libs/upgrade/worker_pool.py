"""Bounded keyed worker pool for per-node bucket fan-out.

The reference processes every state bucket serially and spawns one
detached goroutine per *slow* node action (drain, eviction). At TPU
fleet scale the serial bucket walk itself becomes the bottleneck: a
wave pass performs O(maxUnavailable) independent per-node transitions,
each paying an apiserver write round-trip, strictly one after another.

:class:`BoundedKeyedPool` is the execution substrate the
:class:`~tpu_operator_libs.upgrade.state_manager.ClusterUpgradeStateManager`
fans that work out on:

- **Barrier map** (:meth:`map_wait`): run a batch of thunks on at most
  ``max_workers`` threads and return every result, in input order,
  only once ALL of them finished. A pass's bucket work is therefore
  structurally drained before the next bucket starts — the property
  the chaos harness's crash–restart replay depends on (no node action
  can straddle the "process death" boundary unobserved). The calling
  thread participates as one of the workers, so a pool of size N adds
  N-1 threads and can never deadlock on its own capacity.
- **Keyed fire-and-forget** (:meth:`submit` + :meth:`drain`): the
  generalized form of DrainManager's ``NameSet`` + ``Worker`` seam —
  per-key dedup so the same node is never scheduled twice, a bounded
  thread count instead of one thread per node, and a deterministic
  :meth:`drain` barrier (``join`` alias) tests and the simulator wait
  on.

``async_mode=False`` degrades every path to inline sequential
execution — the same determinism seam :class:`~tpu_operator_libs.util.
Worker` offers, so seeded tests can opt out of real threads entirely.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

logger = logging.getLogger(__name__)


class BoundedKeyedPool:
    """Bounded worker pool with keyed dedup and deterministic drain."""

    def __init__(self, max_workers: int = 8, async_mode: bool = True,
                 name: str = "bucket-pool") -> None:
        if max_workers < 1:
            raise ValueError("max_workers must be >= 1")
        self.max_workers = max_workers
        self.async_mode = async_mode
        self._name = name
        self._cond = threading.Condition()
        self._queue: list[tuple[Callable[[], None], Optional[str]]] = []
        self._in_flight: set[str] = set()
        self._pending = 0          # queued + running fire-and-forget tasks
        self._drainers = 0         # live fire-and-forget worker threads

    # ------------------------------------------------------------------
    # barrier map (bucket fan-out)
    # ------------------------------------------------------------------
    def map_wait(self, thunks: "list[Callable[[], object]]") -> list:
        """Run every thunk, at most ``max_workers`` at a time; return
        results in input order once ALL completed (the barrier). The
        first exception (by input order) is re-raised after the barrier
        — by then every other thunk has still run, which is a superset
        of the serial semantics (idempotent passes re-derive anyway).
        """
        n = len(thunks)
        if n == 0:
            return []
        if not self.async_mode or self.max_workers == 1 or n == 1:
            return [thunk() for thunk in thunks]
        results: list = [None] * n
        errors: list = [None] * n
        cursor = [0]
        cursor_lock = threading.Lock()

        def run() -> None:
            while True:
                with cursor_lock:
                    i = cursor[0]
                    if i >= n:
                        return
                    cursor[0] = i + 1
                try:
                    results[i] = thunks[i]()
                except BaseException as exc:  # noqa: BLE001 — re-raised below
                    errors[i] = exc

        helpers = [threading.Thread(target=run, daemon=True,
                                    name=f"{self._name}-map-{i}")
                   for i in range(min(self.max_workers, n) - 1)]
        for t in helpers:
            t.start()
        run()  # the caller is a worker too: no idle blocking, no deadlock
        for t in helpers:
            t.join()
        for exc in errors:
            if exc is not None:
                raise exc
        return results

    # ------------------------------------------------------------------
    # keyed fire-and-forget (Worker/NameSet generalization)
    # ------------------------------------------------------------------
    def submit(self, fn: Callable[[], None],
               key: Optional[str] = None) -> bool:
        """Schedule ``fn``; with ``key`` given, dedup against in-flight
        work for the same key (returns False when already scheduled —
        the atomic NameSet test-and-set). Exceptions are logged, never
        propagated (worker boundary, like :class:`~tpu_operator_libs.
        util.Worker` threads dying silently in the reference)."""
        with self._cond:
            if key is not None:
                if key in self._in_flight:
                    return False
                self._in_flight.add(key)
            if not self.async_mode:
                self._pending += 1
            else:
                self._queue.append((fn, key))
                self._pending += 1
                if self._drainers < min(self.max_workers, len(self._queue)):
                    self._drainers += 1
                    threading.Thread(
                        target=self._drain_loop, daemon=True,
                        name=f"{self._name}-worker").start()
                return True
        # inline mode: run outside the lock, then settle bookkeeping
        try:
            self._run_one(fn, key)
        finally:
            with self._cond:
                self._pending -= 1
                self._cond.notify_all()
        return True

    def _run_one(self, fn: Callable[[], None], key: Optional[str]) -> None:
        try:
            fn()
        except Exception:  # noqa: BLE001 — worker boundary
            logger.exception("%s: submitted task failed", self._name)
        finally:
            if key is not None:
                with self._cond:
                    self._in_flight.discard(key)

    def _drain_loop(self) -> None:
        while True:
            with self._cond:
                if not self._queue:
                    self._drainers -= 1
                    return
                fn, key = self._queue.pop(0)
            try:
                self._run_one(fn, key)
            finally:
                with self._cond:
                    self._pending -= 1
                    self._cond.notify_all()

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every submitted task finished (the deterministic
        shutdown barrier); True when fully drained within ``timeout``."""
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._cond:
            while self._pending > 0:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def join(self, timeout: float = 30.0) -> None:
        """Worker-interface alias for :meth:`drain`."""
        self.drain(timeout)

    def in_flight(self, key: str) -> bool:
        with self._cond:
            return key in self._in_flight
