"""CapacityBudgetController: traffic-aware dynamic disruption budgets.

``maxUnavailable`` is a static count, but a serving fleet's real
constraint is capacity headroom: how many decode nodes can be out of
service RIGHT NOW without the remaining ones failing to absorb live
traffic. The Ironwood retrospective (PAPERS.md) frames fleet resilience
as continuously routing work *around* disruption rather than pausing
it, and the upgrade-duration-prediction line of work shows admission
must react to live conditions, not a fixed plan. This module is the
admission-side half of that:

- Every reconcile pass the controller samples the fleet's
  :class:`~tpu_operator_libs.health.serving_gate.ServingEndpoint`
  signals — in-flight generations, a QPS EWMA derived from completed
  counters, per-node capacity — and recomputes the **effective**
  disruption budget: the node count that may be unavailable while
  ``live capacity >= demand * (1 + sloHeadroomFraction)`` still holds.
- Traffic troughs raise the effective budget (up to
  ``maxEffectiveBudget``, which may deliberately EXCEED the static
  ``maxUnavailable`` — a peak-safe static count wastes every trough);
  peaks shrink it, and utilization past ``peakPauseUtilization``
  pauses admission outright.
- While the budget is held below the static count, a re-evaluation
  wakeup rides the PR 5 :class:`~tpu_operator_libs.upgrade.nudger.
  DeadlineTimerWheel` (``capacity-trough`` source), so the next trough
  is caught at ``recheckSeconds`` cadence instead of a resync poll.
- When the budget COLLAPSES below what is already unavailable (traffic
  spike, concurrent node kills), the state manager pairs this with the
  safe mid-flight abort arc: drain-phase nodes move to
  ``abort-required`` and return to service (see
  ``state_manager.process_abort_required_nodes``).

The controller holds no durable state: every signal is re-derived from
the live endpoints each pass, so an operator crash-restart (or a shard
takeover) resumes with at most one pass of EWMA warm-up — and its
first-pass demand estimate is the instantaneous in-flight count, which
is the conservative side. Without a wired endpoint source it fails
open to the static budget exactly (non-serving fleets keep reference
semantics, bit for bit).

Composition with the sharded control plane (PR 7/8): the effective
budget replaces the GLOBAL ``B`` fed into ``split_budget`` — the
per-shard share ledger, the decrease-now/increase-next-pass spend rule
and the global clamp all operate on the capacity-derived number, so
shards jointly respect the traffic picture the same way they jointly
respect the static one. Every replica must therefore read the same
fleet-level endpoint source (docs/traffic-aware-budgets.md).
"""

from __future__ import annotations

import logging
import math
import threading
from typing import TYPE_CHECKING, Callable, Optional, Sequence

from tpu_operator_libs.util import Clock

if TYPE_CHECKING:  # pragma: no cover - types only
    from tpu_operator_libs.api.upgrade_policy import CapacityBudgetSpec
    from tpu_operator_libs.upgrade.nudger import ReconcileNudger

logger = logging.getLogger(__name__)

#: node name -> that node's serving endpoints (ServingEndpoint-shaped:
#: ``in_flight``, ``completed``, ``draining``, optional ``capacity``).
#: Deployment-specific, like the serving gate's EndpointResolver — a
#: fleet registry, a label-driven lookup, etc.
EndpointSource = Callable[[], "Mapping[str, Sequence[object]]"]


class CapacityBudgetController:
    """Recomputes the effective disruption budget from live load.

    One instance per state manager, kept across passes (its EWMAs are
    the only in-memory state, and they are advisory — safety never
    depends on them because the instantaneous in-flight count always
    wins on the demand side).
    """

    def __init__(self, spec: "CapacityBudgetSpec",
                 source: Optional[EndpointSource] = None,
                 clock: Optional[Clock] = None,
                 nudger: Optional["ReconcileNudger"] = None) -> None:
        self.spec = spec
        self._source = source
        self._clock = clock or Clock()
        self.nudger = nudger
        self._lock = threading.Lock()
        # demand EWMA (generations) and QPS EWMA (completions/second)
        self._demand_ewma: Optional[float] = None
        self._qps_ewma: Optional[float] = None
        self._last_completed: Optional[int] = None
        self._last_sample_at: Optional[float] = None
        #: Status block of the most recent evaluation
        #: (cluster_status["capacity"] feed). None until the first
        #: pass with the controller enabled.
        self.last_status: Optional[dict] = None
        #: Lifetime counters (metrics.observe_capacity feed).
        self.aborts_total = 0
        self.window_aborts_total = 0
        self.slo_breach_ticks_total = 0
        self.pause_passes_total = 0
        #: Seconds each completed abort took (abort-required entry ->
        #: upgrade-required commit), buffered until the next metrics
        #: drain. Best-effort in-memory: an abort resumed by a fresh
        #: incarnation completes correctly but its duration sample is
        #: lost with the process that started it.
        self._abort_seconds: list[float] = []
        self._abort_started: dict[str, float] = {}
        #: True while the effective budget is CONTRACTING (this pass's
        #: value below the previous pass's): the admission-side
        #: hysteresis signal. Admitting into a falling budget is churn
        #: by construction — the node would be aborted a pass later as
        #: the spike keeps ramping — so the state manager freezes NEW
        #: admissions while this holds (aborts still trim the excess).
        self.budget_falling = False
        self._last_effective: Optional[int] = None

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------
    def set_source(self, source: Optional[EndpointSource]) -> None:
        self._source = source

    @property
    def has_signal(self) -> bool:
        """True when an endpoint source is wired AND currently reports
        at least one endpoint — the condition under which the
        controller modulates at all."""
        if self._source is None:
            return False
        status = self.last_status
        return bool(status and status.get("servingNodes"))

    # ------------------------------------------------------------------
    # the per-pass evaluation
    # ------------------------------------------------------------------
    def effective_budget(self, static_budget: int,
                         now: Optional[float] = None) -> int:
        """One evaluation: sample the endpoints, update the EWMAs, and
        return the effective disruption budget for this pass.

        ``static_budget`` is the policy ``maxUnavailable`` already
        scaled against the fleet (or, under sharding, the global ``B``
        about to be split). With no endpoint signal it is returned
        unchanged — fail-open to static.
        """
        spec = self.spec
        if now is None:
            now = self._clock.now()
        endpoints = self._sample()
        if endpoints is None:
            self.last_status = None
            self.budget_falling = False
            self._last_effective = None
            return static_budget

        per_node_default = spec.per_node_capacity
        serving_nodes = 0
        available_nodes = 0
        in_flight = 0
        completed = 0
        capacity_available = 0
        capacity_total = 0
        # per-traffic-class picture (the class-SLO/status feed; empty
        # for endpoints predating the traffic_class field)
        classes: dict[str, dict] = {}
        for _, eps in endpoints:
            if not eps:
                continue
            serving_nodes += 1
            node_capacity = 0
            admitting = False
            for ep in eps:
                declared = getattr(ep, "capacity", None)
                ep_capacity = (declared if declared
                               else per_node_default)
                node_capacity += ep_capacity
                in_flight += ep.in_flight
                completed += ep.completed
                if not ep.draining:
                    admitting = True
                cls_name = getattr(ep, "traffic_class", "")
                if cls_name:
                    cell = classes.setdefault(
                        cls_name, {"endpoints": 0, "inFlight": 0,
                                   "capacityAdmitting": 0})
                    cell["endpoints"] += 1
                    cell["inFlight"] += ep.in_flight
                    if not ep.draining:
                        cell["capacityAdmitting"] += ep_capacity
            capacity_total += node_capacity
            if admitting:
                available_nodes += 1
                capacity_available += node_capacity
        if serving_nodes == 0:
            # a wired source with nothing behind it (fleet warming up):
            # same fail-open as no source at all
            self.last_status = None
            self.budget_falling = False
            self._last_effective = None
            return static_budget

        with self._lock:
            a = spec.smoothing
            if self._demand_ewma is None:
                self._demand_ewma = float(in_flight)
            else:
                self._demand_ewma = (a * in_flight
                                     + (1.0 - a) * self._demand_ewma)
            if (self._last_completed is not None
                    and self._last_sample_at is not None
                    and now > self._last_sample_at):
                qps = max(0, completed - self._last_completed) \
                    / (now - self._last_sample_at)
                self._qps_ewma = (qps if self._qps_ewma is None
                                  else a * qps + (1.0 - a) * self._qps_ewma)
            self._last_completed = completed
            self._last_sample_at = now
            demand_ewma = self._demand_ewma
            qps_ewma = self._qps_ewma

        # The instantaneous count always wins on the way UP: a spike
        # must shrink the budget on the very pass it appears, while the
        # EWMA smooths the way DOWN so one quiet tick does not open the
        # floodgates.
        demand = max(float(in_flight), demand_ewma)
        per_node = capacity_total / serving_nodes
        required_nodes = math.ceil(
            demand * (1.0 + spec.slo_headroom_fraction)
            / max(1.0, per_node))
        spare = serving_nodes - required_nodes
        utilization = (demand / capacity_available
                       if capacity_available > 0 else float("inf"))
        slo_breached = capacity_available < demand
        if slo_breached:
            self.slo_breach_ticks_total += 1

        ceiling = (spec.max_effective_budget
                   if spec.max_effective_budget > 0 else static_budget)
        paused = utilization >= spec.peak_pause_utilization
        if paused:
            effective = min(spec.min_effective_budget, ceiling)
            self.pause_passes_total += 1
        else:
            effective = max(spec.min_effective_budget,
                            min(spare, ceiling))
        effective = max(0, effective)

        self.budget_falling = (self._last_effective is not None
                               and effective < self._last_effective)
        self._last_effective = effective

        if effective < static_budget and self.nudger is not None:
            # trough-window scheduling: the budget is being held down —
            # re-evaluate at the recheck cadence instead of waiting for
            # the next resync/poll to notice the trough
            self.nudger.nudge_after(spec.recheck_seconds,
                                    "capacity-trough")

        self.last_status = {
            "servingNodes": serving_nodes,
            "availableNodes": available_nodes,
            "inFlight": in_flight,
            "demand": round(demand, 2),
            "qpsEwma": (round(qps_ewma, 3)
                        if qps_ewma is not None else None),
            "capacityAvailable": capacity_available,
            "capacityTotal": capacity_total,
            "headroom": capacity_available - round(demand, 2),
            "utilization": (round(utilization, 4)
                            if capacity_available > 0 else None),
            "requiredNodes": required_nodes,
            "staticBudget": static_budget,
            "effectiveBudget": effective,
            "paused": paused,
            "falling": self.budget_falling,
            "sloBreached": slo_breached,
            "abortsTotal": self.aborts_total + self.window_aborts_total,
            "sloBreachTicksTotal": self.slo_breach_ticks_total,
            "classes": {name: dict(cell)
                        for name, cell in sorted(classes.items())},
        }
        if effective != static_budget:
            logger.info(
                "capacity budget: demand %.1f / capacity %d over %d "
                "serving node(s) -> effective budget %d (static %d%s)",
                demand, capacity_available, serving_nodes, effective,
                static_budget, ", PAUSED" if paused else "")
        return effective

    def _sample(self) -> "Optional[list[tuple[str, Sequence[object]]]]":
        if self._source is None:
            return None
        try:
            mapping = self._source()
        except Exception as exc:  # noqa: BLE001 — signal boundary: a
            # broken source must degrade to static, never wedge a pass
            logger.warning("capacity endpoint source raised (%s); "
                           "falling back to the static budget", exc)
            return None
        return sorted(mapping.items())

    # ------------------------------------------------------------------
    # abort bookkeeping (state manager hooks)
    # ------------------------------------------------------------------
    def note_abort_started(self, node: str, now: float,
                           window: bool = False) -> None:
        """A node entered abort-required this pass."""
        if window:
            self.window_aborts_total += 1
        else:
            self.aborts_total += 1
        with self._lock:
            self._abort_started[node] = now

    def note_abort_finished(self, node: str, now: float) -> None:
        """A node's abort committed back to upgrade-required."""
        with self._lock:
            started = self._abort_started.pop(node, None)
            if started is not None:
                self._abort_seconds.append(max(0.0, now - started))

    def drain_abort_durations(self) -> "list[float]":
        """Completed abort durations since the last drain (the
        ``capacity_abort_seconds`` histogram feed)."""
        with self._lock:
            out, self._abort_seconds = self._abort_seconds, []
        return out
