"""Cost-aware predictive wave planning: learned per-node phase durations.

"Cost-aware Duration Prediction for Software Upgrades in Datacenters"
(PAPERS.md) makes the case that per-node duration *prediction* is the
input makespan optimization actually needs: with a heterogeneous fleet,
admitting nodes in arbitrary (sorted-name) order lets one straggler
start in the last wave and pace the whole rollout. This module supplies
both halves:

- :class:`PhaseDurationPredictor` — online per-node / per-phase duration
  learning. The upgrade flow decomposes into three observable phases
  (the same seams the PR 5 nudger wakes on):

  * ``drain``    — cordon committed → workloads evicted
                   (cordon-required through drain-required),
  * ``restart``  — runtime pod deleted → new pod Ready
                   (pod-restart-required),
  * ``validate`` — validation gate entered → node back in service
                   (validation-required + uncordon-required).

  Phase entry is stamped as a node annotation riding the SAME merge
  patch as the state-label commit (crash-atomic), so a restarted
  operator — or the next shard owner after a takeover — closes the
  in-flight phase's sample from durable state alone, and the most
  recent per-phase durations are mirrored into a second annotation the
  next incarnation seeds its per-node model from. In memory the model
  is a per-(node, phase) EWMA with a fleet-pooled bucketed histogram as
  the cold-start fallback (quantiles via the shared
  ``metrics.quantile_from_buckets`` estimator — bounded memory at 100k
  nodes, no sample lists).

- :class:`PredictiveWavePlanner` — wraps any inner
  :class:`~tpu_operator_libs.upgrade.state_manager.UpgradePlanner`
  (flat, slice-atomic, canary-gated) and composes waves by predicted
  duration: **longest-processing-time-first** ordering, so the
  slowest-predicted nodes start in the first wave and never pace an
  otherwise-finished fleet, while the PR 5 eager refill naturally
  backfills freed slots with the short-predicted remainder. Ties keep
  the candidates' input order (a stable sort), so with zero history the
  plan degrades to exactly the inner planner's flat order — cold start
  is reference behavior, bit for bit. The wrapper also enforces the
  ``maintenanceWindow`` policy ("finish by 06:00 or don't start"): a
  node whose *conservative* predicted completion crosses the window
  close is deferred — left in upgrade-required, never started and
  stranded mid-flow at the close — and every plan emits a predicted
  fleet makespan + per-wave breakdown for ``cluster_status``.
"""

from __future__ import annotations

import logging
import threading
from typing import TYPE_CHECKING, Callable, Optional

from tpu_operator_libs.consts import (
    IN_PROGRESS_STATES,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.upgrade.estimators import (
    PooledHistogram,
    ewma_update,
)
from tpu_operator_libs.util import Clock

if TYPE_CHECKING:  # pragma: no cover - import cycle guard
    from tpu_operator_libs.k8s.objects import Node
    from tpu_operator_libs.upgrade.state_manager import (
        ClusterUpgradeState,
        NodeUpgradeState,
        UpgradePlanner,
    )

logger = logging.getLogger(__name__)

#: The learned phases, in flow order.
PHASES: tuple[str, ...] = ("drain", "restart", "validate")

#: Upgrade-state label value -> phase it belongs to. States outside the
#: map (idle, failed, rollback) carry no phase: their dwell time is not
#: an upgrade cost (failure dwell would poison the model).
PHASE_OF_STATE: dict[str, str] = {
    str(UpgradeState.CORDON_REQUIRED): "drain",
    str(UpgradeState.WAIT_FOR_JOBS_REQUIRED): "drain",
    str(UpgradeState.POD_DELETION_REQUIRED): "drain",
    str(UpgradeState.DRAIN_REQUIRED): "drain",
    str(UpgradeState.POD_RESTART_REQUIRED): "restart",
    str(UpgradeState.VALIDATION_REQUIRED): "validate",
    str(UpgradeState.UNCORDON_REQUIRED): "validate",
}

#: Transitions into these states ABORT the open phase: the elapsed time
#: includes a failure dwell (or, for abort-required, a deliberately
#: truncated drain the fleet called off), so the sample is dropped, not
#: recorded — a half-run phase would poison the duration model.
_ABORT_STATES = frozenset((str(UpgradeState.FAILED),
                           str(UpgradeState.ROLLBACK_REQUIRED),
                           str(UpgradeState.ABORT_REQUIRED)))

#: Pooled-histogram buckets (seconds): per-phase durations ride pod
#: recreate/ready and validation-settle timescales, seconds to hours.
PHASE_SECONDS_BUCKETS: tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 20.0, 30.0, 45.0, 60.0, 90.0, 120.0, 180.0,
    300.0, 600.0, 1200.0, 1800.0, 3600.0, 7200.0)

#: Forecast-error-ratio buckets (|predicted-actual|/actual): sub-percent
#: through 5x — a warm model lands in the low buckets, a cold or
#: drifting one in the tail.
ERROR_RATIO_BUCKETS: tuple[float, ...] = (
    0.01, 0.02, 0.05, 0.1, 0.15, 0.2, 0.3, 0.5, 0.75, 1.0, 1.5, 2.0,
    3.0, 5.0)

#: Relative half-width assumed for confidence bounds while ZERO
#: forecasts have closed: deliberately wide (±50%) so a cold preflight
#: reports honest uncertainty instead of fabricated precision.
COLD_START_ERROR_RATIO = 0.5


class PhaseDurationPredictor:
    """Online per-node / per-phase upgrade-duration model.

    Wire :meth:`observe_transition` as the state provider's
    ``transition_observer``: it is invoked inside the durable-commit
    seam for every state transition, closes/opens phase samples against
    the node's durable phase-start stamp, and returns the annotation
    updates that must ride the transition's merge patch (one wire
    write, crash-atomic). Everything else is read-side.
    """

    def __init__(self, keys: Optional[UpgradeKeys] = None,
                 clock: Optional[Clock] = None,
                 smoothing: float = 0.5,
                 prior_seconds: float = 120.0,
                 conservative_quantile: float = 0.95,
                 conservative_factor: float = 1.25) -> None:
        if not 0.0 < smoothing <= 1.0:
            raise ValueError("smoothing must be in (0, 1]")
        self.keys = keys or UpgradeKeys()
        self._clock = clock or Clock()
        self.smoothing = smoothing
        #: Per-phase prior when NOTHING is known (cold fleet): the
        #: window gate treats an unknown node as costing this much per
        #: phase, which is deliberately conservative.
        self.prior_seconds = prior_seconds
        #: Window-gating pessimism: unknown nodes cost the pooled
        #: ``conservative_quantile``; known nodes cost EWMA x factor.
        self.conservative_quantile = conservative_quantile
        self.conservative_factor = conservative_factor
        # One coarse lock over every model mutation: the observer runs
        # inside the provider's commit path, which executes on bucket
        # worker-pool threads and async drain/eviction workers
        # concurrently — a lost sample or torn EWMA update would be
        # silent model drift. Prediction reads ride the same lock via
        # the mutating read-through seed.
        self._lock = threading.Lock()
        # per-(node, phase) EWMA seconds
        self._ewma: dict[str, dict[str, float]] = {}
        # fleet-pooled per-phase histograms (cold-start fallback);
        # shared estimator — same arithmetic as the precursor model
        self._pooled: dict[str, PooledHistogram] = {
            phase: PooledHistogram(PHASE_SECONDS_BUCKETS)
            for phase in PHASES}
        #: whole-node forecasts opened at flow entry:
        #: node -> (t_entry, predicted_total_seconds)
        self._inflight: dict[str, tuple[float, float]] = {}
        #: (phase, seconds) samples since the last metrics drain.
        self._sample_buffer: list[tuple[str, float]] = []
        #: |predicted - actual| / actual ratios since the last drain.
        self._error_buffer: list[float] = []
        #: RETAINED forecast-error-ratio pool (the drain buffer above
        #: only feeds metrics and empties): confidence bounds read the
        #: model's own lifetime error here — bounds widen as observed
        #: error grows instead of being invented.
        self._error_hist = PooledHistogram(ERROR_RATIO_BUCKETS)
        #: lifetime accounting
        self.samples_total = 0
        self.forecasts_closed_total = 0

    # ------------------------------------------------------------------
    # learning side (provider transition observer)
    # ------------------------------------------------------------------
    def observe_transition(self, node: "Node", old_label: str,
                           new_label: str,
                           ) -> "Optional[dict[str, Optional[str]]]":
        """Close/open phase samples for one durable state transition.

        ``node`` is the LIVE node (pre-patch); returns annotation
        updates (value None deletes) to merge into the transition's
        patch, or None when nothing needs stamping.
        """
        now = self._clock.now()
        name = node.metadata.name
        annotations = node.metadata.annotations
        stamp_key = self.keys.phase_start_annotation
        hist_key = self.keys.phase_durations_annotation
        stamp_phase, stamp_at = _parse_stamp(annotations.get(stamp_key))
        new_phase = PHASE_OF_STATE.get(new_label)
        updates: dict[str, Optional[str]] = {}

        if stamp_phase is not None and stamp_phase != new_phase:
            if new_label not in _ABORT_STATES:
                seconds = max(0.0, now - stamp_at)
                self._record_sample(name, stamp_phase, seconds)
                history = decode_durations(annotations.get(hist_key))
                history[stamp_phase] = round(seconds, 1)
                updates[hist_key] = encode_durations(history)
            else:
                # failure dwell would poison the model: drop the sample
                # and the open forecast
                with self._lock:
                    self._inflight.pop(name, None)

        if new_phase is None:
            if stamp_phase is not None or stamp_key in annotations:
                updates[stamp_key] = None
            if new_label == str(UpgradeState.DONE):
                # forecast closes against the whole-node wall clock;
                # the phase-durations annotation is deliberately KEPT:
                # it is the per-node model's durable half — the next
                # operator incarnation (or the next shard owner, or the
                # NEXT rollout after a crash) predicts this node from
                # cluster state alone. Benches comparing against a
                # predictor-less run exclude exactly these two keys
                # from their fingerprints.
                self._close_forecast(name, now)
        elif stamp_phase != new_phase:
            updates[stamp_key] = f"{new_phase}:{now:.3f}"
            if stamp_phase is None:
                # entering the phased flow: open the whole-node forecast
                predicted = self.predict_node(name, annotations)
                with self._lock:
                    self._inflight[name] = (now, predicted)
        return updates or None

    def _record_sample(self, name: str, phase: str,
                       seconds: float) -> None:
        with self._lock:
            per_node = self._ewma.setdefault(name, {})
            per_node[phase] = ewma_update(per_node.get(phase), seconds,
                                          self.smoothing)
            self._pooled[phase].record(seconds)
            self._sample_buffer.append((phase, seconds))
            self.samples_total += 1

    def _close_forecast(self, name: str, now: float) -> None:
        with self._lock:
            opened = self._inflight.pop(name, None)
            if opened is None:
                return
            t0, predicted = opened
            actual = now - t0
            if actual > 0.0:
                ratio = abs(predicted - actual) / actual
                self._error_buffer.append(ratio)
                self._error_hist.record(ratio)
                self.forecasts_closed_total += 1

    # ------------------------------------------------------------------
    # prediction side
    # ------------------------------------------------------------------
    def predict_phase(self, name: str, phase: str,
                      annotations: "Optional[dict[str, str]]" = None,
                      conservative: bool = False) -> float:
        """Predicted seconds for one node's phase: per-node EWMA, else
        the node's durable phase-durations annotation (the takeover /
        crash-recovery seed), else the fleet pool, else the prior."""
        with self._lock:
            per_node = self._ewma.get(name, {}).get(phase)
            if per_node is None and annotations:
                durable = decode_durations(annotations.get(
                    self.keys.phase_durations_annotation))
                per_node = durable.get(phase)
                if per_node is not None:
                    # read-through: the durable seed becomes the
                    # in-memory model so later passes agree without
                    # re-parsing
                    self._ewma.setdefault(name, {})[phase] = per_node
        if per_node is not None:
            return per_node * (self.conservative_factor
                               if conservative else 1.0)
        pooled = self._pooled[phase]
        if pooled.count:
            q = self.conservative_quantile if conservative else 0.5
            estimate = pooled.quantile(q)
            if estimate is not None:
                return estimate
        return self.prior_seconds

    def predict_node(self, name: str,
                     annotations: "Optional[dict[str, str]]" = None,
                     conservative: bool = False) -> float:
        """Predicted whole-flow seconds for one node (sum of phases)."""
        return sum(
            self.predict_phase(name, phase, annotations, conservative)
            for phase in PHASES)

    def error_ratio(self, q: float = 0.9) -> float:
        """The model's observed |predicted-actual|/actual forecast-error
        ratio at quantile ``q``, from the RETAINED error pool (closed
        whole-node forecasts). Cold start — zero closed forecasts —
        returns :data:`COLD_START_ERROR_RATIO`: honest, wide
        uncertainty instead of fabricated precision."""
        with self._lock:
            if self._error_hist.count:
                estimate = self._error_hist.quantile(q)
                if estimate is not None:
                    return estimate
        return COLD_START_ERROR_RATIO

    @property
    def error_samples(self) -> int:
        """Closed forecasts retained in the error pool."""
        with self._lock:
            return self._error_hist.count

    def confidence_interval(self, phase: "Optional[str]" = None,
                            q: float = 0.9) -> "tuple[float, float]":
        """``(lower, upper)`` seconds bound for a fleet-typical node's
        ``phase`` (whole flow when None), widened multiplicatively by
        the model's own observed forecast error at quantile ``q`` —
        the consumer of the forecast-error histogram that was
        previously recorded and then only drained to metrics. Bounds
        WIDEN as observed error grows; a warm, accurate model tightens
        them."""
        phases = (phase,) if phase is not None else PHASES
        for p in phases:
            if p not in PHASES:
                raise ValueError(f"unknown phase {p!r}")
        base = sum(self.predict_phase("", p) for p in phases)
        ratio = self.error_ratio(q)
        return max(0.0, base * (1.0 - ratio)), base * (1.0 + ratio)

    def remaining_seconds(self, name: str, state_label: str,
                          annotations: "Optional[dict[str, str]]" = None,
                          now: Optional[float] = None) -> float:
        """Predicted seconds left for an IN-FLIGHT node: the current
        phase's prediction minus the time already spent in it (from the
        durable stamp), plus every later phase."""
        phase = PHASE_OF_STATE.get(state_label)
        if phase is None:
            # failed/rollback: no phase clock runs; assume a full pass
            return self.predict_node(name, annotations)
        if now is None:
            now = self._clock.now()
        index = PHASES.index(phase)
        remaining = sum(self.predict_phase(name, later, annotations)
                        for later in PHASES[index + 1:])
        current = self.predict_phase(name, phase, annotations)
        elapsed = 0.0
        if annotations:
            stamp_phase, stamp_at = _parse_stamp(
                annotations.get(self.keys.phase_start_annotation))
            if stamp_phase == phase:
                elapsed = max(0.0, now - stamp_at)
        return remaining + max(0.0, current - elapsed)

    # ------------------------------------------------------------------
    # evidence feed (observe_planner)
    # ------------------------------------------------------------------
    def drain_phase_samples(self) -> "list[tuple[str, float]]":
        """(phase, seconds) samples observed since the last drain."""
        with self._lock:
            out, self._sample_buffer = self._sample_buffer, []
        return out

    def drain_forecast_errors(self) -> "list[float]":
        """|predicted-actual|/actual ratios closed since the last
        drain."""
        with self._lock:
            out, self._error_buffer = self._error_buffer, []
        return out

    @property
    def known_nodes(self) -> int:
        with self._lock:
            return len(self._ewma)

    def pooled_stats(self) -> "dict[str, dict]":
        """Per-phase pooled (count, mean, p50, p95) — the model's own
        evidence, read through the shared quantile estimator."""
        out = {}
        with self._lock:
            for phase, pooled in self._pooled.items():
                out[phase] = {
                    "count": pooled.count,
                    "mean": (round(pooled.total / pooled.count, 2)
                             if pooled.count else None),
                    "p50": (round(pooled.quantile(0.5), 2)
                            if pooled.count else None),
                    "p95": (round(pooled.quantile(0.95), 2)
                            if pooled.count else None),
                }
        return out


def _parse_stamp(value: Optional[str],
                 ) -> "tuple[Optional[str], float]":
    """``<phase>:<epoch>`` -> (phase, epoch); (None, 0.0) when absent or
    malformed (a garbled stamp reads as "no open phase" — the sample is
    lost, never invented)."""
    if not value:
        return None, 0.0
    phase, sep, raw = value.partition(":")
    if not sep or phase not in PHASES:
        return None, 0.0
    try:
        return phase, float(raw)
    except ValueError:
        return None, 0.0


def decode_durations(value: Optional[str]) -> "dict[str, float]":
    """``drain=12.5,restart=40`` -> {phase: seconds} (unknown phases and
    malformed entries are dropped)."""
    out: dict[str, float] = {}
    if not value:
        return out
    for entry in value.split(","):
        phase, sep, raw = entry.partition("=")
        if not sep or phase not in PHASES:
            continue
        try:
            out[phase] = float(raw)
        except ValueError:
            continue
    return out


def encode_durations(durations: "dict[str, float]") -> str:
    return ",".join(f"{phase}={durations[phase]:g}"
                    for phase in PHASES if phase in durations)


class PredictiveWavePlanner:
    """LPT wave composition + maintenance-window gating over any inner
    planner.

    Lives on the state manager across passes (like the multislice
    constraint): the wrapper itself is stateless per plan, but it
    carries the fleet ETA of the most recent plan for
    ``cluster_status`` and the lifetime window-deferral counter for
    metrics. ``audit`` (optional) receives
    ``(kind, node, at, predicted_done)`` for every ``"admit"`` /
    ``"defer"`` decision — the chaos monitor's maintenance-window
    invariant feed.
    """

    def __init__(self, inner: "UpgradePlanner",
                 predictor: PhaseDurationPredictor,
                 clock: Optional[Clock] = None,
                 window: "Optional[object]" = None,
                 audit: "Optional[Callable[[str, str, float, float], None]]"
                 = None) -> None:
        self.inner = inner
        self.predictor = predictor
        self._clock = clock or Clock()
        #: Optional MaintenanceWindowSpec (api/upgrade_policy.py).
        self.window = window
        self.audit = audit
        #: Status block of the most recent plan (cluster_status feed).
        self.last_plan: Optional[dict] = None
        #: Lifetime nodes deferred by the maintenance window.
        self.deferred_by_window_total = 0

    def _window_close(self, now: float) -> Optional[float]:
        window = self.window
        if window is None or not getattr(window, "enable", False):
            return None
        resolve = getattr(window, "close_at", None)
        if resolve is not None:
            return resolve(now)
        return None

    def plan(self, candidates: "list[NodeUpgradeState]", available: int,
             state: "ClusterUpgradeState") -> "list[NodeUpgradeState]":
        now = self._clock.now()
        predictions: dict[str, float] = {}
        for ns in candidates:
            name = ns.node.metadata.name
            predictions[name] = self.predictor.predict_node(
                name, ns.node.metadata.annotations)

        close = self._window_close(now)
        eligible = list(candidates)
        deferred: list[str] = []
        if close is not None:
            margin = float(getattr(self.window, "margin_seconds", 0) or 0)
            eligible = []
            for ns in candidates:
                name = ns.node.metadata.name
                bound = self.predictor.predict_node(
                    name, ns.node.metadata.annotations, conservative=True)
                if now + bound + margin > close:
                    # "finish by the close or don't start": the node
                    # stays in upgrade-required and is reconsidered
                    # next pass (the model may tighten, or the next
                    # window may open)
                    deferred.append(name)
                    if self.audit is not None:
                        self.audit("defer", name, now, now + bound)
                    continue
                eligible.append(ns)
            if deferred:
                self.deferred_by_window_total += len(deferred)
                logger.info(
                    "maintenance window (close in %.0fs) deferred %d "
                    "node(s): predicted completion would cross it",
                    close - now, len(deferred))

        # LPT: slowest-predicted first. The sort is STABLE and the key
        # is the prediction alone, so equal predictions (cold start:
        # everything is the prior) keep the candidates' input order —
        # zero history degrades to the inner planner's flat order.
        ordered = sorted(
            eligible, key=lambda ns: -predictions[ns.node.metadata.name])
        selected = self.inner.plan(ordered, available, state)
        if self.audit is not None:
            for ns in selected:
                name = ns.node.metadata.name
                bound = self.predictor.predict_node(
                    name, ns.node.metadata.annotations, conservative=True)
                self.audit("admit", name, now, now + bound)
        self.last_plan = self._eta(state, candidates, predictions, now,
                                   available, frozenset(deferred), close)
        return selected

    # ------------------------------------------------------------------
    # fleet makespan ETA (cluster_status feed)
    # ------------------------------------------------------------------
    def _eta(self, state: "ClusterUpgradeState",
             candidates: "list[NodeUpgradeState]",
             predictions: "dict[str, float]", now: float, available: int,
             deferred: "frozenset[str]",
             close: Optional[float]) -> dict:
        """Predicted fleet makespan by LPT multiprocessor packing: every
        in-flight node occupies a slot loaded with its predicted
        remaining seconds; pending nodes are assigned longest-first to
        the least-loaded slot. The slot count is the current in-flight
        window (in-progress + available) — the budget the throttle
        actually spends."""
        import heapq

        in_progress: list[float] = []
        for bucket_state in IN_PROGRESS_STATES:
            for ns in state.bucket(bucket_state):
                in_progress.append(self.predictor.remaining_seconds(
                    ns.node.metadata.name, str(bucket_state),
                    ns.node.metadata.annotations, now))
        # Pending work = this plan's candidates plus anything else still
        # sitting in upgrade-required (e.g. canary-held nodes the inner
        # planner will filter), minus window-deferred nodes: the ETA
        # answers "when does the work that MAY run finish" — deferred
        # nodes are reported separately, not folded into a makespan
        # they will never join.
        seen: set[str] = set()
        pending: list[float] = []
        for ns in list(candidates) \
                + list(state.bucket(UpgradeState.UPGRADE_REQUIRED)):
            name = ns.node.metadata.name
            if name in seen or name in deferred:
                continue
            seen.add(name)
            pending.append(predictions.get(
                name, self.predictor.predict_node(
                    name, ns.node.metadata.annotations)))
        pending.sort(reverse=True)
        slots = max(1, len(in_progress) + max(0, available))
        loads = in_progress + [0.0] * max(0, slots - len(in_progress))
        heapq.heapify(loads)
        for job in pending:
            heapq.heappush(loads, heapq.heappop(loads) + job)
        makespan = max(loads) if (in_progress or pending) else 0.0

        waves = []
        for i in range(0, len(pending), slots):
            chunk = pending[i:i + slots]
            waves.append({"nodes": len(chunk),
                          "predictedSeconds": round(chunk[0], 1)})
        plan: dict = {
            "predictedMakespanSeconds": round(makespan, 1),
            "predictedDoneAtSeconds": round(now + makespan, 1),
            "inProgress": len(in_progress),
            "pending": len(pending),
            "slots": slots,
            "waves": waves,
            "coldStart": self.predictor.samples_total == 0,
        }
        if close is not None:
            plan["windowCloseSeconds"] = round(close, 1)
            plan["deferredByWindow"] = len(deferred)
            plan["fitsWindow"] = bool(now + makespan <= close)
        return plan
