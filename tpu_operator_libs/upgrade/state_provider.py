"""Synchronized node access: the only writer of upgrade state.

Equivalent of the reference NodeUpgradeStateProvider
(node_upgrade_state_provider.go:33-216). Every state transition in the
system funnels through ``change_node_upgrade_state`` — the label write *is*
the durable commit point of the state machine.

Like the reference, after a successful patch the provider polls the node
back until the change is visible (node_upgrade_state_provider.go:92-117):
the consumer operator's informer cache may lag the API server, and the next
reconcile must see its own writes. Poll interval and timeout are injectable
(the reference hardcodes 1 s / 10 s).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from tpu_operator_libs.consts import (
    GKE_NODEPOOL_LABEL,
    NULL_STRING,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.k8s.client import K8sClient
from tpu_operator_libs.k8s.objects import Node
from tpu_operator_libs.util import Clock, EventRecorder, Event, KeyedLock, log_event

logger = logging.getLogger(__name__)


class CacheSyncTimeout(TimeoutError):
    """The patched value never became visible within the sync timeout."""


class NodeUpgradeStateProvider:
    """Get nodes and change their upgrade state/annotations atomically."""

    def __init__(self, client: K8sClient, keys: UpgradeKeys,
                 recorder: Optional[EventRecorder] = None,
                 clock: Optional[Clock] = None,
                 sync_timeout: float = 10.0,
                 poll_interval: float = 1.0,
                 fence: Optional[Callable[[str, str], None]] = None,
                 ) -> None:
        self._client = client
        self._keys = keys
        self._recorder = recorder
        self._clock = clock or Clock()
        self._sync_timeout = sync_timeout
        self._poll_interval = poll_interval
        self._node_lock = KeyedLock()
        self._counter_lock = threading.Lock()
        # Sharded-control-plane split-brain gate: called with
        # (node_name, nodepool) immediately before EVERY durable write.
        # A replica deposed from the node's shard raises
        # k8s.sharding.ShardFencedError HERE — inside the per-node lock,
        # before the patch — so a stale pass's queued transition writes
        # are rejected, never silently applied outside its partition.
        self._fence = fence
        # Transition-observation seam (upgrade/predictor.py): called
        # with (live_node, current_label, new_label) inside the commit
        # path, AFTER the stale-snapshot precondition passed and BEFORE
        # the patch is issued. Whatever annotation updates it returns
        # ride the transition's merge patch — one wire write, so the
        # observer's bookkeeping (phase-start stamps, duration history)
        # is crash-atomic with the state commit it describes. An
        # observer failure must never block the transition: it is
        # logged and the commit proceeds unstamped.
        self.transition_observer: Optional[Callable[
            [Node, str, str], "Optional[dict[str, Optional[str]]]"]] = None
        #: Durable node writes issued (each is one wire patch).
        self.writes_total = 0
        #: Wire patches avoided by coalescing a transition's label +
        #: annotation changes into one merge patch (metrics evidence
        #: for the fleet-scale write path).
        self.coalesced_writes_saved_total = 0

    def with_fence(self, fence: Optional[Callable[[str, str], None]],
                   ) -> "NodeUpgradeStateProvider":
        """Install (or clear) the shard fence after construction."""
        self._fence = fence
        return self

    def _check_fence(self, node: Node) -> None:
        if self._fence is not None:
            self._fence(node.metadata.name,
                        node.metadata.labels.get(GKE_NODEPOOL_LABEL, ""))

    def _count_write(self, saved: int = 0) -> None:
        with self._counter_lock:
            self.writes_total += 1
            self.coalesced_writes_saved_total += saved

    @property
    def keys(self) -> UpgradeKeys:
        return self._keys

    def get_node(self, name: str) -> Node:
        """Fetch a fresh snapshot of the node
        (node_upgrade_state_provider.go:59-68)."""
        with self._node_lock.lock(name):
            return self._client.get_node(name)

    def change_node_upgrade_state(
            self, node: Node, new_state: UpgradeState | str,
            annotations: "Optional[dict[str, Optional[str]]]" = None,
    ) -> bool:
        """Patch the upgrade-state label and wait until the change is
        readable back (node_upgrade_state_provider.go:72-134).

        ``node`` is updated in place on success, so later processing within
        the same reconcile pass observes the new state — matching the
        reference, which Gets into the caller's node object.

        ``annotations`` (value None/"null" deletes the key) ride the
        SAME merge patch as the label when given — the coalesced-write
        path: bookkeeping that belongs to the transition (the
        initial-state marker, a consumed timer stamp) commits
        atomically with it, in one wire round-trip instead of two, and
        an operator crash can no longer land between the two writes.
        The annotations are only applied when the state precondition
        passes — a skipped (stale-snapshot) transition patches nothing.

        **Optimistic concurrency (beyond-reference):** the write only
        lands if the node's live state label still equals the label in
        the caller's ``node`` snapshot; otherwise it is skipped and
        ``False`` is returned. A pass (or detached worker) holding a
        stale snapshot must not regress a node another pass has already
        advanced — the reference avoids that race only by convention
        (one reconcile goroutine per consumer); here concurrent
        reconciles are supported, so the label write carries the
        precondition, the way a Kubernetes update carries its
        resourceVersion. The skipped caller's next reconcile re-derives
        the correct action from the fresh label.
        """
        value = str(new_state)
        ann_patch = {key: (None if v is None or v == NULL_STRING else v)
                     for key, v in (annotations or {}).items()}
        expected = node.metadata.labels.get(self._keys.state_label, "")
        with self._node_lock.lock(node.metadata.name):
            live = self._client.get_node(node.metadata.name)
            current = live.metadata.labels.get(self._keys.state_label, "")
            if current not in (expected, value):
                logger.warning(
                    "node %s state is %r, not %r: snapshot is stale; "
                    "skipping transition to %r",
                    node.metadata.name, current or "unknown",
                    expected or "unknown", value)
                return False
            if current == value and not ann_patch:
                # another pass already committed this exact transition
                # (its own observer stamped it — nothing to observe)
                self._copy_into(node, live)
                return True
            observer = self.transition_observer
            if observer is not None:
                try:
                    extra = observer(live, current, value)
                except Exception as exc:  # noqa: BLE001 — observation
                    # must never block the commit
                    logger.warning(
                        "transition observer failed for node %s "
                        "(%r -> %r): %s; committing unstamped",
                        node.metadata.name, current, value, exc)
                    extra = None
                if extra:
                    for key, extra_value in extra.items():
                        # explicit caller annotations win on collision
                        ann_patch.setdefault(key, extra_value)
            self._check_fence(node)
            try:
                if ann_patch:
                    self._client.patch_node_meta(
                        node.metadata.name,
                        labels={self._keys.state_label: value},
                        annotations=ann_patch)
                    self._count_write(saved=1)
                else:
                    self._client.patch_node_labels(
                        node.metadata.name, {self._keys.state_label: value})
                    self._count_write()
            except Exception as exc:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to update node state label to {value}: {exc}")
                raise

            def check(n: Node) -> bool:
                if n.metadata.labels.get(
                        self._keys.state_label, "") != value:
                    return False
                return all(
                    key not in n.metadata.annotations if v is None
                    else n.metadata.annotations.get(key) == v
                    for key, v in ann_patch.items())

            try:
                fresh = self._wait_visible(node.metadata.name, check)
            except CacheSyncTimeout:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to observe node state label {value} after patch")
                raise
        self._copy_into(node, fresh)
        logger.info("node %s upgrade state -> %s", node.metadata.name, value)
        log_event(self._recorder, node, Event.NORMAL, self._keys.event_reason,
                  f"Successfully updated node state label to {value}")
        return True

    def change_node_upgrade_annotations(
            self, node: Node,
            annotations: "dict[str, Optional[str]]") -> None:
        """Patch SEVERAL node annotations as one merge patch (value None
        deletes the key) and wait for visibility.

        The single patch is the crash-atomicity seam: bookkeeping that
        must move together — e.g. the remediation machine's attempt
        counter and action-start stamp — would otherwise be two wire
        writes with a window between them, and an operator crash inside
        that window leaves durable state the resumed instance
        misreads (a half-stamped attempt double-bills the escalation
        budget). One merge patch commits all-or-nothing, exactly like
        the label write that is the state machine's commit point."""
        if not annotations:
            return
        patch = {key: (None if value is None or value == NULL_STRING
                       else value)
                 for key, value in annotations.items()}
        with self._node_lock.lock(node.metadata.name):
            self._check_fence(node)
            try:
                self._client.patch_node_annotations(
                    node.metadata.name, patch)
                self._count_write()
            except Exception as exc:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to update node annotations "
                          f"{sorted(patch)}: {exc}")
                raise

            def check(n: Node) -> bool:
                return all(
                    key not in n.metadata.annotations if value is None
                    else n.metadata.annotations.get(key) == value
                    for key, value in patch.items())

            try:
                fresh = self._wait_visible(node.metadata.name, check)
            except CacheSyncTimeout:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to observe node annotations "
                          f"{sorted(patch)} after patch")
                raise
        self._copy_into(node, fresh)
        log_event(self._recorder, node, Event.NORMAL,
                  self._keys.event_reason,
                  f"Successfully updated node annotations {sorted(patch)}")

    def change_node_upgrade_annotation(self, node: Node, key: str,
                                       value: Optional[str]) -> None:
        """Patch (or with value None / "null" delete) a node annotation and
        wait for visibility (node_upgrade_state_provider.go:138-216)."""
        delete = value is None or value == NULL_STRING
        patch_value = None if delete else value
        with self._node_lock.lock(node.metadata.name):
            self._check_fence(node)
            try:
                self._client.patch_node_annotations(
                    node.metadata.name, {key: patch_value})
                self._count_write()
            except Exception as exc:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to update node annotation {key}={value}: {exc}")
                raise
            if delete:
                check = lambda n: key not in n.metadata.annotations  # noqa: E731
            else:
                check = lambda n: n.metadata.annotations.get(key) == value  # noqa: E731
            try:
                fresh = self._wait_visible(node.metadata.name, check)
            except CacheSyncTimeout:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to observe node annotation {key}={value}")
                raise
        self._copy_into(node, fresh)
        log_event(self._recorder, node, Event.NORMAL, self._keys.event_reason,
                  f"Successfully updated node annotation {key}={value}")

    def _wait_visible(self, name: str, predicate) -> Node:
        deadline = self._clock.now() + self._sync_timeout
        while True:
            fresh = self._client.get_node(name)
            if predicate(fresh):
                return fresh
            if self._clock.now() >= deadline:
                raise CacheSyncTimeout(
                    f"node {name} update not visible within "
                    f"{self._sync_timeout}s")
            self._clock.sleep(self._poll_interval)

    @staticmethod
    def _copy_into(node: Node, fresh: Node) -> None:
        node.metadata.labels = fresh.metadata.labels
        node.metadata.annotations = fresh.metadata.annotations
        node.metadata.resource_version = fresh.metadata.resource_version
        node.spec = fresh.spec
        node.status = fresh.status
