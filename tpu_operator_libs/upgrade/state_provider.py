"""Synchronized node access: the only writer of upgrade state.

Equivalent of the reference NodeUpgradeStateProvider
(node_upgrade_state_provider.go:33-216). Every state transition in the
system funnels through ``change_node_upgrade_state`` — the label write *is*
the durable commit point of the state machine.

Like the reference, after a successful patch the provider polls the node
back until the change is visible (node_upgrade_state_provider.go:92-117):
the consumer operator's informer cache may lag the API server, and the next
reconcile must see its own writes. Poll interval and timeout are injectable
(the reference hardcodes 1 s / 10 s).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from tpu_operator_libs.consts import (
    GKE_NODEPOOL_LABEL,
    NULL_STRING,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.k8s.client import ConflictError, K8sClient
from tpu_operator_libs.k8s.objects import Node
from tpu_operator_libs.util import Clock, EventRecorder, Event, KeyedLock, log_event

logger = logging.getLogger(__name__)

#: Kubernetes rejects objects whose total annotation payload exceeds
#: 256KiB (TotalAnnotationSizeLimitB). The provider enforces the budget
#: client side at write time so a runaway stamp (an unbounded duration
#: history, a pathological trace id) degrades to a truncated-but-audited
#: value instead of poisoning EVERY subsequent write to the node with
#: apiserver validation failures.
DEFAULT_ANNOTATION_BUDGET_BYTES = 256 * 1024


class CacheSyncTimeout(TimeoutError):
    """The patched value never became visible within the sync timeout."""


class NodeUpgradeStateProvider:
    """Get nodes and change their upgrade state/annotations atomically."""

    def __init__(self, client: K8sClient, keys: UpgradeKeys,
                 recorder: Optional[EventRecorder] = None,
                 clock: Optional[Clock] = None,
                 sync_timeout: float = 10.0,
                 poll_interval: float = 1.0,
                 fence: Optional[Callable[[str, str], None]] = None,
                 conflict_retries: int = 3,
                 max_annotation_bytes: Optional[int]
                 = DEFAULT_ANNOTATION_BUDGET_BYTES,
                 audit: "Optional[object]" = None,
                 ) -> None:
        self._client = client
        self._keys = keys
        self._recorder = recorder
        self._clock = clock or Clock()
        self._sync_timeout = sync_timeout
        self._poll_interval = poll_interval
        # 409 handling: a ConflictError means the write LOST A RACE
        # (resourceVersion moved between read and write), not that the
        # server hiccupped — blind re-raise would abort the pass and
        # blind retry would spin against a hot peer. Each retry
        # refetches the live object and rechecks the precondition
        # before reissuing; a storm outlasting the budget parks the
        # transition (return False) instead of wedging the reconcile.
        self._conflict_retries = max(0, conflict_retries)
        # Per-object annotation byte budget (None disables the guard).
        self._max_annotation_bytes = max_annotation_bytes
        # Optional DecisionAudit: truncations are recorded as audited
        # decisions, not just log lines — durable state was altered.
        self._audit = audit
        self._node_lock = KeyedLock()
        self._counter_lock = threading.Lock()
        # Sharded-control-plane split-brain gate: called with
        # (node_name, nodepool) immediately before EVERY durable write.
        # A replica deposed from the node's shard raises
        # k8s.sharding.ShardFencedError HERE — inside the per-node lock,
        # before the patch — so a stale pass's queued transition writes
        # are rejected, never silently applied outside its partition.
        self._fence = fence
        # Transition-observation seam (upgrade/predictor.py): called
        # with (live_node, current_label, new_label) inside the commit
        # path, AFTER the stale-snapshot precondition passed and BEFORE
        # the patch is issued. Whatever annotation updates it returns
        # ride the transition's merge patch — one wire write, so the
        # observer's bookkeeping (phase-start stamps, duration history)
        # is crash-atomic with the state commit it describes. An
        # observer failure must never block the transition: it is
        # logged and the commit proceeds unstamped.
        self.transition_observer: Optional[Callable[
            [Node, str, str], "Optional[dict[str, Optional[str]]]"]] = None
        #: Durable node writes issued (each is one wire patch).
        self.writes_total = 0
        #: Wire patches avoided by coalescing a transition's label +
        #: annotation changes into one merge patch (metrics evidence
        #: for the fleet-scale write path).
        self.coalesced_writes_saved_total = 0
        #: 409-conflict write attempts retried after refetch+recheck.
        self.conflict_retries_total = 0
        #: Transitions parked because a conflict storm outlasted the
        #: retry budget (the caller's next reconcile re-derives).
        self.conflict_parks_total = 0
        #: Annotation bytes dropped by the per-object size guard.
        self.annotation_bytes_truncated_total = 0

    def with_fence(self, fence: Optional[Callable[[str, str], None]],
                   ) -> "NodeUpgradeStateProvider":
        """Install (or clear) the shard fence after construction."""
        self._fence = fence
        return self

    def _check_fence(self, node: Node) -> None:
        if self._fence is not None:
            self._fence(node.metadata.name,
                        node.metadata.labels.get(GKE_NODEPOOL_LABEL, ""))

    def _count_write(self, saved: int = 0) -> None:
        with self._counter_lock:
            self.writes_total += 1
            self.coalesced_writes_saved_total += saved

    def _guard_annotation_budget(
            self, node: Node,
            patch: "dict[str, Optional[str]]",
    ) -> "dict[str, Optional[str]]":
        """Clamp ``patch`` so the node's merged annotation payload stays
        under the byte budget. NEW values are truncated largest-first
        (deterministic: size then key order) and the truncation is
        audited + evented — the write NEVER fails on size, because a
        rejected patch would wedge every later transition on the node
        behind one runaway stamp. Pre-existing oversized annotations are
        left alone (this guard owns only bytes it is about to write).
        The base size uses the caller's snapshot, not a fresh read:
        the budget is a safety clamp, not an exact invariant, and one
        extra wire read per write is the wrong trade."""
        budget = self._max_annotation_bytes
        if budget is None or not patch:
            return patch
        merged = dict(node.metadata.annotations)
        for key, value in patch.items():
            if value is None:
                merged.pop(key, None)
            else:
                merged[key] = value
        total = sum(len(k.encode("utf-8")) + len(v.encode("utf-8"))
                    for k, v in merged.items())
        over = total - budget
        if over <= 0:
            return patch
        out = dict(patch)
        victims = sorted(
            ((key, value) for key, value in patch.items()
             if value is not None),
            key=lambda kv: (-len(kv[1].encode("utf-8")), kv[0]))
        dropped = 0
        truncated: list[str] = []
        for key, value in victims:
            if over <= 0:
                break
            raw = value.encode("utf-8")
            keep = max(0, len(raw) - over)
            # decode(errors="ignore") heals a slice landing mid-rune
            out[key] = raw[:keep].decode("utf-8", errors="ignore")
            over -= len(raw) - keep
            dropped += len(raw) - keep
            truncated.append(key)
        if truncated:
            with self._counter_lock:
                self.annotation_bytes_truncated_total += dropped
            logger.warning(
                "node %s: annotation patch exceeds %d-byte budget; "
                "truncated %d bytes from %s",
                node.metadata.name, budget, dropped, truncated)
            log_event(self._recorder, node, Event.WARNING,
                      self._keys.event_reason,
                      f"Annotation byte budget exceeded; truncated "
                      f"{dropped} bytes from {sorted(truncated)}")
            if self._audit is not None:
                self._audit.record(
                    "annotation-budget", node.metadata.name,
                    decision="truncate", rule="size-guard/truncate",
                    inputs={"budget": budget, "droppedBytes": dropped,
                            "keys": ",".join(sorted(truncated))})
        return out

    def _patch_with_conflict_retry(
            self, node: Node, issue: Callable[[], None],
            recheck: "Optional[Callable[[Node], bool]]" = None,
            describe: str = "write", reraise: bool = False) -> bool:
        """Issue a durable write, absorbing a bounded number of 409s.

        Each conflict refetches the live node and — when ``recheck`` is
        given — re-validates the caller's precondition against it before
        reissuing (409 means the object MOVED; reissuing blind could
        commit a decision derived from a dead snapshot). Returns False
        when the precondition no longer holds (lost the race to a real
        writer) or the storm outlasts the retry budget (park: the
        caller's next reconcile re-derives from fresh state). With
        ``reraise`` the exhausted storm re-raises the ConflictError
        instead of parking — for annotation writes whose callers speak
        exceptions, not booleans. Any other exception propagates
        unchanged."""
        attempt = 0
        while True:
            try:
                issue()
                return True
            except ConflictError as exc:
                attempt += 1
                with self._counter_lock:
                    self.conflict_retries_total += 1
                if attempt > self._conflict_retries:
                    with self._counter_lock:
                        self.conflict_parks_total += 1
                    logger.warning(
                        "node %s: %s hit %d consecutive conflicts; "
                        "parking until next reconcile: %s",
                        node.metadata.name, describe, attempt, exc)
                    log_event(self._recorder, node, Event.WARNING,
                              self._keys.event_reason,
                              f"Sustained write conflicts on {describe}; "
                              f"parked after {attempt} attempts")
                    if reraise:
                        raise
                    return False
                live = self._client.get_node(node.metadata.name)
                if recheck is not None and not recheck(live):
                    logger.warning(
                        "node %s: %s precondition no longer holds after "
                        "conflict; skipping", node.metadata.name, describe)
                    return False
                self._clock.sleep(self._poll_interval * attempt)

    @property
    def keys(self) -> UpgradeKeys:
        return self._keys

    def get_node(self, name: str) -> Node:
        """Fetch a fresh snapshot of the node
        (node_upgrade_state_provider.go:59-68)."""
        with self._node_lock.lock(name):
            return self._client.get_node(name)

    def change_node_upgrade_state(
            self, node: Node, new_state: UpgradeState | str,
            annotations: "Optional[dict[str, Optional[str]]]" = None,
    ) -> bool:
        """Patch the upgrade-state label and wait until the change is
        readable back (node_upgrade_state_provider.go:72-134).

        ``node`` is updated in place on success, so later processing within
        the same reconcile pass observes the new state — matching the
        reference, which Gets into the caller's node object.

        ``annotations`` (value None/"null" deletes the key) ride the
        SAME merge patch as the label when given — the coalesced-write
        path: bookkeeping that belongs to the transition (the
        initial-state marker, a consumed timer stamp) commits
        atomically with it, in one wire round-trip instead of two, and
        an operator crash can no longer land between the two writes.
        The annotations are only applied when the state precondition
        passes — a skipped (stale-snapshot) transition patches nothing.

        **Optimistic concurrency (beyond-reference):** the write only
        lands if the node's live state label still equals the label in
        the caller's ``node`` snapshot; otherwise it is skipped and
        ``False`` is returned. A pass (or detached worker) holding a
        stale snapshot must not regress a node another pass has already
        advanced — the reference avoids that race only by convention
        (one reconcile goroutine per consumer); here concurrent
        reconciles are supported, so the label write carries the
        precondition, the way a Kubernetes update carries its
        resourceVersion. The skipped caller's next reconcile re-derives
        the correct action from the fresh label.
        """
        value = str(new_state)
        ann_patch = {key: (None if v is None or v == NULL_STRING else v)
                     for key, v in (annotations or {}).items()}
        expected = node.metadata.labels.get(self._keys.state_label, "")
        with self._node_lock.lock(node.metadata.name):
            live = self._client.get_node(node.metadata.name)
            current = live.metadata.labels.get(self._keys.state_label, "")
            if current not in (expected, value):
                logger.warning(
                    "node %s state is %r, not %r: snapshot is stale; "
                    "skipping transition to %r",
                    node.metadata.name, current or "unknown",
                    expected or "unknown", value)
                return False
            if current == value and not ann_patch:
                # another pass already committed this exact transition
                # (its own observer stamped it — nothing to observe)
                self._copy_into(node, live)
                return True
            observer = self.transition_observer
            if observer is not None:
                try:
                    extra = observer(live, current, value)
                except Exception as exc:  # noqa: BLE001 — observation
                    # must never block the commit
                    logger.warning(
                        "transition observer failed for node %s "
                        "(%r -> %r): %s; committing unstamped",
                        node.metadata.name, current, value, exc)
                    extra = None
                if extra:
                    for key, extra_value in extra.items():
                        # explicit caller annotations win on collision
                        ann_patch.setdefault(key, extra_value)
            self._check_fence(node)
            ann_patch = self._guard_annotation_budget(node, ann_patch)

            def issue() -> None:
                if ann_patch:
                    self._client.patch_node_meta(
                        node.metadata.name,
                        labels={self._keys.state_label: value},
                        annotations=ann_patch)
                    self._count_write(saved=1)
                else:
                    self._client.patch_node_labels(
                        node.metadata.name, {self._keys.state_label: value})
                    self._count_write()

            def still_holds(live_node: Node) -> bool:
                return live_node.metadata.labels.get(
                    self._keys.state_label, "") in (expected, value)

            try:
                committed = self._patch_with_conflict_retry(
                    node, issue, recheck=still_holds,
                    describe=f"state transition to {value!r}")
            except Exception as exc:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to update node state label to {value}: {exc}")
                raise
            if not committed:
                return False

            def check(n: Node) -> bool:
                if n.metadata.labels.get(
                        self._keys.state_label, "") != value:
                    return False
                return all(
                    key not in n.metadata.annotations if v is None
                    else n.metadata.annotations.get(key) == v
                    for key, v in ann_patch.items())

            try:
                fresh = self._wait_visible(node.metadata.name, check)
            except CacheSyncTimeout:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to observe node state label {value} after patch")
                raise
        self._copy_into(node, fresh)
        logger.info("node %s upgrade state -> %s", node.metadata.name, value)
        log_event(self._recorder, node, Event.NORMAL, self._keys.event_reason,
                  f"Successfully updated node state label to {value}")
        return True

    def change_node_upgrade_annotations(
            self, node: Node,
            annotations: "dict[str, Optional[str]]") -> None:
        """Patch SEVERAL node annotations as one merge patch (value None
        deletes the key) and wait for visibility.

        The single patch is the crash-atomicity seam: bookkeeping that
        must move together — e.g. the remediation machine's attempt
        counter and action-start stamp — would otherwise be two wire
        writes with a window between them, and an operator crash inside
        that window leaves durable state the resumed instance
        misreads (a half-stamped attempt double-bills the escalation
        budget). One merge patch commits all-or-nothing, exactly like
        the label write that is the state machine's commit point."""
        if not annotations:
            return
        patch = {key: (None if value is None or value == NULL_STRING
                       else value)
                 for key, value in annotations.items()}
        with self._node_lock.lock(node.metadata.name):
            self._check_fence(node)
            patch = self._guard_annotation_budget(node, patch)

            def issue() -> None:
                self._client.patch_node_annotations(
                    node.metadata.name, patch)
                self._count_write()

            try:
                self._patch_with_conflict_retry(
                    node, issue, describe="annotation patch",
                    reraise=True)
            except Exception as exc:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to update node annotations "
                          f"{sorted(patch)}: {exc}")
                raise

            def check(n: Node) -> bool:
                return all(
                    key not in n.metadata.annotations if value is None
                    else n.metadata.annotations.get(key) == value
                    for key, value in patch.items())

            try:
                fresh = self._wait_visible(node.metadata.name, check)
            except CacheSyncTimeout:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to observe node annotations "
                          f"{sorted(patch)} after patch")
                raise
        self._copy_into(node, fresh)
        log_event(self._recorder, node, Event.NORMAL,
                  self._keys.event_reason,
                  f"Successfully updated node annotations {sorted(patch)}")

    def change_node_upgrade_annotation(self, node: Node, key: str,
                                       value: Optional[str]) -> None:
        """Patch (or with value None / "null" delete) a node annotation and
        wait for visibility (node_upgrade_state_provider.go:138-216)."""
        delete = value is None or value == NULL_STRING
        patch_value = None if delete else value
        with self._node_lock.lock(node.metadata.name):
            self._check_fence(node)
            guarded = self._guard_annotation_budget(
                node, {key: patch_value})
            patch_value = guarded[key]

            def issue() -> None:
                self._client.patch_node_annotations(
                    node.metadata.name, {key: patch_value})
                self._count_write()

            try:
                self._patch_with_conflict_retry(
                    node, issue, describe=f"annotation {key} patch",
                    reraise=True)
            except Exception as exc:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to update node annotation {key}={value}: {exc}")
                raise
            if delete:
                check = lambda n: key not in n.metadata.annotations  # noqa: E731
            else:
                check = lambda n: n.metadata.annotations.get(key) == patch_value  # noqa: E731
            try:
                fresh = self._wait_visible(node.metadata.name, check)
            except CacheSyncTimeout:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to observe node annotation {key}={value}")
                raise
        self._copy_into(node, fresh)
        log_event(self._recorder, node, Event.NORMAL, self._keys.event_reason,
                  f"Successfully updated node annotation {key}={value}")

    def _wait_visible(self, name: str, predicate) -> Node:
        deadline = self._clock.now() + self._sync_timeout
        while True:
            fresh = self._client.get_node(name)
            if predicate(fresh):
                return fresh
            if self._clock.now() >= deadline:
                raise CacheSyncTimeout(
                    f"node {name} update not visible within "
                    f"{self._sync_timeout}s")
            self._clock.sleep(self._poll_interval)

    @staticmethod
    def _copy_into(node: Node, fresh: Node) -> None:
        node.metadata.labels = fresh.metadata.labels
        node.metadata.annotations = fresh.metadata.annotations
        node.metadata.resource_version = fresh.metadata.resource_version
        node.spec = fresh.spec
        node.status = fresh.status
