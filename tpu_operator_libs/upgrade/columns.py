"""Columnar (struct-of-arrays) reconcile core.

The per-object hot path tops out around 100k nodes: the incremental
fleet census, the sharded canary cohort domain and the budget split all
walk Python dicts of Node objects, and `BENCH_shard.json` measured
60-73 s snapshot builds per replica at 102400 nodes. This module is the
columnar replacement: fleet-level facts live in parallel numpy arrays
keyed by a stable node index, so state classification, per-shard census
recounts and budget accounting become whole-array ops (bincount over
``shard * n_codes + state_code``) instead of per-node dict walks.

Two layers:

- :class:`CensusColumns` — the production store behind
  ``ClusterUpgradeStateManager``'s partition-reads census. Built
  incrementally from informer deltas (one ``update``/``remove`` per
  changed node), it answers the per-shard census, the shard totals the
  budget split consumes, and the canary-eligible domain — each cached
  against fine-grained version counters so a steady pass where nothing
  relevant changed reuses the previous answer outright. A dict
  fallback (:class:`DictCensus`, the pre-columnar semantics bit for
  bit) stays selectable behind the manager's ``snapshot_mode`` flag,
  and a parity mode cross-checks both per pass.
- :class:`ColumnarFleetEngine` / :class:`DictFleetEngine` — the
  fleet-scale twin kernels behind ``bench-shard-1m``: the same
  triage/budget/LPT-wave rolling-upgrade schedule run once as
  vectorized array ops and once as the per-node dict reference. A
  million-node fleet converges bit-identically (final-state
  fingerprint + makespan) while the columnar side's incremental
  per-pass build stays sub-second — fleet scales FakeCluster object
  graphs cannot reach.

numpy is an optional dependency everywhere: ``HAVE_NUMPY`` gates the
columnar paths and every consumer falls back to the dict semantics
when it is absent.
"""

from __future__ import annotations

import hashlib
import time
from typing import Callable, Iterable, Optional

try:  # pragma: no cover - exercised implicitly by every import
    import numpy as np

    HAVE_NUMPY = True
except Exception:  # pragma: no cover - numpy is baked into the image
    np = None  # type: ignore[assignment]
    HAVE_NUMPY = False

from tpu_operator_libs.consts import ALL_STATES, UpgradeState

#: Stable state-label vocabulary: code = index into ALL_STATES (code 0
#: is UNKNOWN / no label). Labels outside the vocabulary (never emitted
#: by this operator, but labels are user-writable) get dynamic codes
#: appended after the static block.
STATE_CODES: dict[str, int] = {
    str(state): idx for idx, state in enumerate(ALL_STATES)}
_N_STATIC_CODES = len(STATE_CODES)


class CensusColumns:
    """Incremental columnar fleet census over node metadata.

    One row per known node: shard id, state-label code, skip flag and
    pool id, in parallel numpy arrays indexed by a stable per-name row
    (rows are recycled through a free list on removal, so long-lived
    fleets do not grow the arrays unboundedly). All fleet-level
    answers are whole-array reductions:

    - :meth:`per_shard` — ``{shard: {label: count}}``, one bincount;
    - :meth:`shard_totals` — labeled-node count per shard (the budget
      split's denominator);
    - :meth:`eligible` — the sharded canary cohort domain
      (``(name, pool)`` pairs, sorted), cached against the membership
      + labeled-set versions so steady passes whose transitions stay
      within labeled states reuse the previous sorted list outright.

    Thread-free by design: the state manager mutates and reads it from
    the reconcile thread only, like the dict census it replaces.
    """

    def __init__(self, num_shards: int,
                 initial_capacity: int = 1024) -> None:
        if not HAVE_NUMPY:
            raise RuntimeError("CensusColumns requires numpy")
        self.num_shards = int(num_shards)
        cap = max(16, int(initial_capacity))
        self._shard = np.zeros(cap, dtype=np.int32)
        self._state = np.zeros(cap, dtype=np.int16)
        self._skip = np.zeros(cap, dtype=bool)
        self._pool = np.zeros(cap, dtype=np.int32)
        self._alive = np.zeros(cap, dtype=bool)
        self._rows: dict[str, int] = {}
        self._names: list[Optional[str]] = [None] * cap
        self._free: list[int] = list(range(cap - 1, -1, -1))
        # dynamic vocabulary for labels outside ALL_STATES + pools
        self._extra_codes: dict[str, int] = {}
        self._code_labels: list[str] = [str(s) for s in ALL_STATES]
        self._pool_codes: dict[str, int] = {"": 0}
        self._pool_names: list[str] = [""]
        #: Version counters (monotonic): any mutation bumps `version`;
        #: membership (row add/remove) and skip flips bump
        #: `membership_version`; a node's labeled-ness (has any state
        #: label vs none) flipping bumps `labeled_version`. Consumers
        #: key caches on the narrowest counter that can invalidate
        #: their answer.
        self.version = 0
        self.membership_version = 0
        self.labeled_version = 0
        self._census_cache: Optional[tuple[int, dict]] = None
        self._eligible_cache: dict[bool, tuple[int, int, list]] = {}

    # -- vocabulary ----------------------------------------------------
    def _state_code(self, label: str) -> int:
        code = STATE_CODES.get(label)
        if code is not None:
            return code
        code = self._extra_codes.get(label)
        if code is None:
            code = _N_STATIC_CODES + len(self._extra_codes)
            self._extra_codes[label] = code
            self._code_labels.append(label)
        return code

    def _pool_code(self, pool: str) -> int:
        code = self._pool_codes.get(pool)
        if code is None:
            code = len(self._pool_names)
            self._pool_codes[pool] = code
            self._pool_names.append(pool)
        return code

    def _grow(self) -> None:
        old = len(self._shard)
        new = old * 2
        for attr in ("_shard", "_state", "_skip", "_pool", "_alive"):
            arr = getattr(self, attr)
            grown = np.zeros(new, dtype=arr.dtype)
            grown[:old] = arr
            setattr(self, attr, grown)
        self._names.extend([None] * (new - old))
        self._free.extend(range(new - 1, old - 1, -1))

    # -- mutation ------------------------------------------------------
    def update(self, name: str, shard: int, state_label: str,
               skip: bool = False, pool: str = "") -> None:
        """Upsert one node's row (one informer delta)."""
        code = self._state_code(state_label)
        row = self._rows.get(name)
        self.version += 1
        if row is None:
            if not self._free:
                self._grow()
            row = self._free.pop()
            self._rows[name] = row
            self._names[row] = name
            self._alive[row] = True
            self.membership_version += 1
            if code:
                self.labeled_version += 1
        else:
            if bool(self._state[row]) != bool(code):
                self.labeled_version += 1
            if bool(self._skip[row]) != bool(skip) \
                    or self._pool[row] != self._pool_code(pool):
                self.membership_version += 1
        self._shard[row] = shard
        self._state[row] = code
        self._skip[row] = skip
        self._pool[row] = self._pool_code(pool)
        self._census_cache = None

    def remove(self, name: str) -> None:
        row = self._rows.pop(name, None)
        if row is None:
            return
        self.version += 1
        self.membership_version += 1
        if self._state[row]:
            self.labeled_version += 1
        self._alive[row] = False
        self._state[row] = 0
        self._names[row] = None
        self._free.append(row)
        self._census_cache = None

    def rebuild(self, items: Iterable[tuple[str, int, str, bool, str]],
                ) -> None:
        """Full resync: replace every row from ``(name, shard, label,
        skip, pool)`` tuples. O(fleet), like the dict rebuild it
        replaces — runs only on a full relist or an ownership move."""
        rows = list(items)
        cap = max(16, len(rows))
        self._shard = np.zeros(cap, dtype=np.int32)
        self._state = np.zeros(cap, dtype=np.int16)
        self._skip = np.zeros(cap, dtype=bool)
        self._pool = np.zeros(cap, dtype=np.int32)
        self._alive = np.zeros(cap, dtype=bool)
        self._rows = {}
        self._names = [None] * cap
        for row, (name, shard, label, skip, pool) in enumerate(rows):
            self._rows[name] = row
            self._names[row] = name
            self._shard[row] = shard
            self._state[row] = self._state_code(label)
            self._skip[row] = skip
            self._pool[row] = self._pool_code(pool)
            self._alive[row] = True
        self._free = list(range(cap - 1, len(rows) - 1, -1))
        self.version += 1
        self.membership_version += 1
        self.labeled_version += 1
        self._census_cache = None
        self._eligible_cache = {}

    # -- reads ---------------------------------------------------------
    def __len__(self) -> int:
        return len(self._rows)

    def __contains__(self, name: str) -> bool:
        return name in self._rows

    def entry(self, name: str) -> Optional[tuple[int, str]]:
        """(shard, state-label) recorded for ``name`` — the columnar
        answer to the dict census's ``_census_entries`` lookup."""
        row = self._rows.get(name)
        if row is None:
            return None
        return (int(self._shard[row]),
                self._code_labels[int(self._state[row])])

    def per_shard(self) -> dict[int, dict[str, int]]:
        """``{shard: {state-label: count}}`` over LABELED nodes, as one
        bincount over ``shard * n_codes + state_code``. Cached until
        the next mutation — an idle steady pass pays a dict copy of
        nothing."""
        cached = self._census_cache
        if cached is not None and cached[0] == self.version:
            return cached[1]
        n_codes = len(self._code_labels)
        mask = self._alive & (self._state > 0)
        keys = (self._shard[mask].astype(np.int64) * n_codes
                + self._state[mask])
        counts = np.bincount(keys, minlength=self.num_shards * n_codes)
        census: dict[int, dict[str, int]] = {
            shard: {} for shard in range(self.num_shards)}
        for flat in np.nonzero(counts)[0]:
            shard, code = divmod(int(flat), n_codes)
            census.setdefault(shard, {})[self._code_labels[code]] = \
                int(counts[flat])
        self._census_cache = (self.version, census)
        return census

    def shard_totals(self) -> dict[int, int]:
        """Labeled-node count per shard (the budget split's census)."""
        return {shard: sum(cell.values())
                for shard, cell in self.per_shard().items()}

    def count_in_states(self, labels: Iterable[str]) -> int:
        codes = [self._state_code(label) for label in labels]
        mask = self._alive & np.isin(self._state, codes)
        return int(np.count_nonzero(mask))

    def eligible(self, labeled_only: bool) -> list[tuple[str, str]]:
        """Sorted ``(name, pool)`` pairs of non-skip nodes — the
        sharded canary cohort domain. ``labeled_only`` restricts to
        nodes carrying any state label (the no-node-selector domain).
        Cached against (membership, labeled-set) versions: per-pass
        state transitions BETWEEN labeled states — the steady state of
        a rollout — never invalidate it, which is what removes the
        O(fleet) per-pass cohort walk."""
        key_version = (self.membership_version,
                       self.labeled_version if labeled_only else -1)
        cached = self._eligible_cache.get(labeled_only)
        if cached is not None and (cached[0], cached[1]) == key_version:
            return cached[2]
        mask = self._alive & ~self._skip
        if labeled_only:
            mask = mask & (self._state > 0)
        pairs = sorted(
            (self._names[row], self._pool_names[int(self._pool[row])])
            for row in np.nonzero(mask)[0])
        self._eligible_cache[labeled_only] = (
            key_version[0], key_version[1], pairs)
        return pairs


class DictCensus:
    """The pre-columnar dict census, factored behind the same API so
    the manager's ``snapshot_mode="dict"`` fallback (and the parity
    cross-check) share one code path with the columnar store."""

    def __init__(self, num_shards: int) -> None:
        self.num_shards = int(num_shards)
        self._entries: dict[str, tuple[int, str, bool, str]] = {}
        self._census: dict[int, dict[str, int]] = {
            shard: {} for shard in range(self.num_shards)}
        self.version = 0

    def update(self, name: str, shard: int, state_label: str,
               skip: bool = False, pool: str = "") -> None:
        self.remove(name)
        self._entries[name] = (shard, state_label, skip, pool)
        if state_label:
            cell = self._census.setdefault(shard, {})
            cell[state_label] = cell.get(state_label, 0) + 1
        self.version += 1

    def remove(self, name: str) -> None:
        prev = self._entries.pop(name, None)
        if prev is None:
            return
        shard, label = prev[0], prev[1]
        if label:
            cell = self._census.get(shard)
            if cell is not None and cell.get(label, 0) > 0:
                cell[label] -= 1
                if not cell[label]:
                    del cell[label]
        self.version += 1

    def rebuild(self, items: Iterable[tuple[str, int, str, bool, str]],
                ) -> None:
        self._entries = {}
        self._census = {shard: {}
                        for shard in range(self.num_shards)}
        for name, shard, label, skip, pool in items:
            self._entries[name] = (shard, label, skip, pool)
            if label:
                cell = self._census.setdefault(shard, {})
                cell[label] = cell.get(label, 0) + 1
        self.version += 1

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def entry(self, name: str) -> Optional[tuple[int, str]]:
        row = self._entries.get(name)
        if row is None:
            return None
        return (row[0], row[1])

    def per_shard(self) -> dict[int, dict[str, int]]:
        return {shard: dict(cell)
                for shard, cell in self._census.items()}

    def shard_totals(self) -> dict[int, int]:
        return {shard: sum(cell.values())
                for shard, cell in self._census.items()}

    def eligible(self, labeled_only: bool) -> list[tuple[str, str]]:
        return sorted(
            (name, row[3]) for name, row in self._entries.items()
            if not row[2] and (row[1] or not labeled_only))


def census_equal(a: dict[int, dict[str, int]],
                 b: dict[int, dict[str, int]]) -> bool:
    """Structural equality modulo empty shard cells (the dict census
    drops a shard's cell once its last label count decays; the
    columnar census always reports every shard)."""
    shards = set(a) | set(b)
    return all((a.get(s) or {}) == (b.get(s) or {}) for s in shards)


class ParityCensus:
    """Run the columnar store with the dict census as a live shadow:
    every mutation lands in both, every fleet-level read comes from
    the columnar primary, and every read cross-checks the shadow.
    ``checks``/``mismatches`` feed ``columnar_parity_checks_total``;
    a mismatch logs (once per divergence site) but never raises — the
    parity flag exists to build confidence in production, not to turn
    a counting bug into an outage."""

    def __init__(self, primary: CensusColumns,
                 shadow: DictCensus,
                 on_mismatch: Optional[Callable[[str], None]] = None,
                 ) -> None:
        self.primary = primary
        self.shadow = shadow
        self.num_shards = primary.num_shards
        self.checks = 0
        self.mismatches = 0
        self._on_mismatch = on_mismatch
        self._reported: set[str] = set()

    def _check(self, site: str, ok: bool) -> None:
        self.checks += 1
        if ok:
            return
        self.mismatches += 1
        if site not in self._reported:
            self._reported.add(site)
            if self._on_mismatch is not None:
                self._on_mismatch(site)

    # mutations mirror to both stores
    def update(self, name: str, shard: int, state_label: str,
               skip: bool = False, pool: str = "") -> None:
        self.primary.update(name, shard, state_label, skip, pool)
        self.shadow.update(name, shard, state_label, skip, pool)

    def remove(self, name: str) -> None:
        self.primary.remove(name)
        self.shadow.remove(name)

    def rebuild(self, items: Iterable[tuple[str, int, str, bool, str]],
                ) -> None:
        rows = list(items)
        self.primary.rebuild(rows)
        self.shadow.rebuild(rows)

    # reads answer from the primary, cross-checked
    def __len__(self) -> int:
        return len(self.primary)

    def __contains__(self, name: str) -> bool:
        return name in self.primary

    def entry(self, name: str) -> Optional[tuple[int, str]]:
        got = self.primary.entry(name)
        self._check("entry", got == self.shadow.entry(name))
        return got

    def per_shard(self) -> dict[int, dict[str, int]]:
        got = self.primary.per_shard()
        self._check("per_shard",
                    census_equal(got, self.shadow.per_shard()))
        return got

    def shard_totals(self) -> dict[int, int]:
        got = self.primary.shard_totals()
        shadow = self.shadow.shard_totals()
        self._check("shard_totals",
                    all(got.get(s, 0) == shadow.get(s, 0)
                        for s in set(got) | set(shadow)))
        return got

    def eligible(self, labeled_only: bool) -> list[tuple[str, str]]:
        got = self.primary.eligible(labeled_only)
        self._check("eligible",
                    got == self.shadow.eligible(labeled_only))
        return got


# ======================================================================
# fleet-scale twin kernels (bench-shard-1m)
# ======================================================================

#: Collapsed kernel states: the engines model the budget-visible
#: phases of the rolling upgrade (idle -> admitted/in-flight -> done).
#: The full 13-state machine's intermediate stamps are write-path
#: detail the kernel does not spend memory on at 1M rows.
K_PENDING = 0      # upgrade-required: runtime out of date, not admitted
K_IN_FLIGHT = 1    # admitted: cordoned + pod restart in flight
K_DONE = 2         # converged on the new revision


def synth_fleet(n_nodes: int, num_shards: int, seed: int = 20260807,
                ) -> "tuple[object, object]":
    """Deterministic synthetic fleet: per-node shard ids and restart
    durations (ticks). Shards follow a stable hash of the node index
    (the ShardRing idiom without 1M sha256 calls — the mapping is
    input data here, not the thing under test) and durations are
    seed-pure lognormal-ish integers in [1, 12]."""
    if not HAVE_NUMPY:
        raise RuntimeError("synth_fleet requires numpy")
    rng = np.random.default_rng(seed)
    # multiplicative hashing gives a balanced, order-free shard map
    idx = np.arange(n_nodes, dtype=np.uint64)
    shard = ((idx * np.uint64(2654435761)) >> np.uint64(7)) \
        % np.uint64(num_shards)
    durations = rng.integers(1, 13, size=n_nodes)
    return shard.astype(np.int32), durations.astype(np.int32)


class ColumnarFleetEngine:
    """Vectorized rolling-upgrade kernel over a synthetic fleet.

    Per tick and per replica: finish due in-flight nodes, recount the
    owned shards' census (bincount), derive the replica's budget share
    via the SAME ``split_budget`` the production ledger uses, and
    admit the next LPT wave (duration-descending, index-ascending —
    precomputed argsort order) into the freed slots. All of it is
    whole-array ops; the per-pass cost the bench reports as
    "incremental snapshot build" is exactly this delta-apply +
    recount."""

    def __init__(self, n_nodes: int, num_shards: int,
                 owned_by_replica: "list[frozenset[int]]",
                 budget_fraction: float = 0.25,
                 seed: int = 20260807) -> None:
        if not HAVE_NUMPY:
            raise RuntimeError("ColumnarFleetEngine requires numpy")
        self.n = int(n_nodes)
        self.num_shards = int(num_shards)
        self.owned = [frozenset(o) for o in owned_by_replica]
        self.shard, self.durations = synth_fleet(
            n_nodes, num_shards, seed)
        self.state = np.full(self.n, K_PENDING, dtype=np.int8)
        self.finish_tick = np.full(self.n, -1, dtype=np.int64)
        self.done_tick = np.full(self.n, -1, dtype=np.int64)
        self.budget_fraction = budget_fraction
        #: Per-shard LPT admission order (duration desc, index asc),
        #: precomputed once; a cursor per shard tracks how far the
        #: wave front has advanced — admission is then a slice.
        order = np.lexsort((np.arange(self.n), -self.durations))
        self._lpt_by_shard = {
            s: order[self.shard[order] == s]
            for s in range(self.num_shards)}
        self._cursor = {s: 0 for s in range(self.num_shards)}
        #: Watch accounting: state transitions per tick land in the
        #: owning replica's stream (server-side sharded watch); the
        #: fleet-wide count is the single-owner baseline.
        self.events_by_replica = [0] * len(self.owned)
        self.events_total = 0
        self.full_fleet_lists = [0] * len(self.owned)
        self.build_seconds = [0.0] * len(self.owned)
        self.build_passes = 0
        self.max_build_seconds = 0.0

    def _global_budget(self) -> int:
        import math

        return int(math.ceil(self.n * self.budget_fraction))

    def tick(self, now: int) -> int:
        """One reconcile round across every replica; returns the number
        of state transitions committed this tick."""
        from tpu_operator_libs.k8s.sharding import split_budget

        transitions = 0
        budget = self._global_budget()
        # the deterministic split every replica derives identically
        totals = np.bincount(self.shard, minlength=self.num_shards)
        counts = {s: int(totals[s]) for s in range(self.num_shards)}
        entitled = split_budget(budget, counts)
        for replica, owned in enumerate(self.owned):
            started = time.perf_counter()
            owned_arr = np.fromiter(owned, dtype=np.int32)
            owned_mask = np.isin(self.shard, owned_arr)
            # 1. finish due in-flight nodes (the delta apply)
            due = owned_mask & (self.state == K_IN_FLIGHT) \
                & (self.finish_tick <= now)
            n_due = int(np.count_nonzero(due))
            if n_due:
                self.state[due] = K_DONE
                self.done_tick[due] = now
                transitions += n_due
                self.events_by_replica[replica] += n_due
                self.events_total += n_due
            # 2. recount + budget share (vectorized census)
            in_flight = int(np.count_nonzero(
                owned_mask & (self.state == K_IN_FLIGHT)))
            share = sum(entitled[s] for s in owned)
            slots = max(0, share - in_flight)
            # 3. admit the next LPT wave into the freed slots
            admitted = 0
            for s in owned:
                if admitted >= slots:
                    break
                lpt = self._lpt_by_shard[s]
                cur = self._cursor[s]
                take = lpt[cur:cur + (slots - admitted)]
                if take.size == 0:
                    continue
                self._cursor[s] = cur + take.size
                self.state[take] = K_IN_FLIGHT
                self.finish_tick[take] = now + self.durations[take]
                admitted += int(take.size)
            if admitted:
                transitions += admitted
                self.events_by_replica[replica] += admitted
                self.events_total += admitted
            elapsed = time.perf_counter() - started
            self.build_seconds[replica] += elapsed
            self.max_build_seconds = max(self.max_build_seconds,
                                         elapsed)
        self.build_passes += 1
        return transitions

    def converged(self) -> bool:
        return bool(np.all(self.state == K_DONE))

    def fingerprint(self) -> str:
        """Order-independent digest of (index, final state, done tick)
        — must equal the dict twin's bit for bit."""
        payload = np.stack(
            [np.arange(self.n, dtype=np.int64),
             self.state.astype(np.int64), self.done_tick]).tobytes()
        return hashlib.sha256(payload).hexdigest()[:16]


class DictFleetEngine:
    """Per-node dict reference twin of :class:`ColumnarFleetEngine`:
    the identical schedule executed one node at a time over Python
    dicts (the pre-columnar idiom). Shard map and durations come from
    the same :func:`synth_fleet` arrays, so any fingerprint divergence
    is an engine bug, not input skew."""

    def __init__(self, n_nodes: int, num_shards: int,
                 owned_by_replica: "list[frozenset[int]]",
                 budget_fraction: float = 0.25,
                 seed: int = 20260807) -> None:
        shard, durations = synth_fleet(n_nodes, num_shards, seed)
        self.n = int(n_nodes)
        self.num_shards = int(num_shards)
        self.owned = [frozenset(o) for o in owned_by_replica]
        self.shard = [int(s) for s in shard]
        self.durations = [int(d) for d in durations]
        self.state = {i: K_PENDING for i in range(self.n)}
        self.finish_tick: dict[int, int] = {}
        self.done_tick = {i: -1 for i in range(self.n)}
        self.budget_fraction = budget_fraction
        by_shard: dict[int, list[int]] = {
            s: [] for s in range(self.num_shards)}
        for i in range(self.n):
            by_shard[self.shard[i]].append(i)
        for s, members in by_shard.items():
            members.sort(key=lambda i: (-self.durations[i], i))
        self._lpt_by_shard = by_shard
        self._cursor = {s: 0 for s in range(self.num_shards)}
        self._in_flight: dict[int, set[int]] = {
            s: set() for s in range(self.num_shards)}
        self.build_seconds = [0.0] * len(self.owned)

    def _global_budget(self) -> int:
        import math

        return int(math.ceil(self.n * self.budget_fraction))

    def tick(self, now: int) -> int:
        from tpu_operator_libs.k8s.sharding import split_budget

        transitions = 0
        budget = self._global_budget()
        counts: dict[int, int] = {s: 0 for s in range(self.num_shards)}
        for i in range(self.n):
            counts[self.shard[i]] += 1
        entitled = split_budget(budget, counts)
        for replica, owned in enumerate(self.owned):
            started = time.perf_counter()
            for s in owned:
                for i in sorted(self._in_flight[s]):
                    if self.finish_tick.get(i, -1) <= now:
                        self.state[i] = K_DONE
                        self.done_tick[i] = now
                        self._in_flight[s].discard(i)
                        transitions += 1
            share = sum(entitled[s] for s in owned)
            in_flight = sum(len(self._in_flight[s]) for s in owned)
            slots = max(0, share - in_flight)
            for s in owned:
                if slots <= 0:
                    break
                lpt = self._lpt_by_shard[s]
                cur = self._cursor[s]
                while cur < len(lpt) and slots > 0:
                    i = lpt[cur]
                    cur += 1
                    self.state[i] = K_IN_FLIGHT
                    self.finish_tick[i] = now + self.durations[i]
                    self._in_flight[s].add(i)
                    slots -= 1
                    transitions += 1
                self._cursor[s] = cur
            self.build_seconds[replica] += \
                time.perf_counter() - started
        return transitions

    def converged(self) -> bool:
        return all(s == K_DONE for s in self.state.values())

    def fingerprint(self) -> str:
        if HAVE_NUMPY:
            state = np.fromiter(
                (self.state[i] for i in range(self.n)),
                dtype=np.int64, count=self.n)
            done = np.fromiter(
                (self.done_tick[i] for i in range(self.n)),
                dtype=np.int64, count=self.n)
            payload = np.stack(
                [np.arange(self.n, dtype=np.int64), state,
                 done]).tobytes()
            return hashlib.sha256(payload).hexdigest()[:16]
        digest = hashlib.sha256()
        for i in range(self.n):
            digest.update(
                f"{i}:{self.state[i]}:{self.done_tick[i]};".encode())
        return digest.hexdigest()[:16]


def run_engine(engine: "object", max_ticks: int = 100_000,
               ) -> dict:
    """Drive either twin to convergence; returns makespan +
    fingerprint + per-replica accounting."""
    ticks = 0
    while not engine.converged():
        if ticks >= max_ticks:
            raise RuntimeError("engine did not converge")
        engine.tick(ticks)
        ticks += 1
    out = {
        "makespan_ticks": ticks,
        "fingerprint": engine.fingerprint(),
        "build_seconds": [round(s, 4) for s in engine.build_seconds],
    }
    events = getattr(engine, "events_by_replica", None)
    if events is not None:
        out["events_by_replica"] = list(events)
        out["events_total"] = engine.events_total
        out["full_fleet_lists"] = list(engine.full_fleet_lists)
        out["build_passes"] = engine.build_passes
        out["max_build_seconds"] = round(engine.max_build_seconds, 4)
    return out


__all__ = [
    "HAVE_NUMPY",
    "STATE_CODES",
    "CensusColumns",
    "DictCensus",
    "ParityCensus",
    "census_equal",
    "ColumnarFleetEngine",
    "DictFleetEngine",
    "synth_fleet",
    "run_engine",
    "K_PENDING",
    "K_IN_FLIGHT",
    "K_DONE",
]

# keep the UpgradeState import "used" for consumers introspecting codes
_ = UpgradeState
