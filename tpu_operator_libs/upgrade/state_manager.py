"""ClusterUpgradeStateManager — the cluster-wide upgrade state machine.

Equivalent of the reference's upgrade_state.go:40-1120. One reconcile is:

1. ``build_state``: snapshot every runtime pod + its DaemonSet + its node,
   bucketed by the node's upgrade-state label (upgrade_state.go:214-279).
2. ``apply_state``: one pass over the buckets in fixed order, moving each
   node at most one transition along the graph (upgrade_state.go:364-484):

   unknown ─┬─(pod in sync)──────────────────────────→ upgrade-done
            └─(out of sync | safe-load | requested)──→ upgrade-required
   upgrade-required ─(slot available)→ cordon-required
   cordon-required ─(cordon ok)→ wait-for-jobs-required
   wait-for-jobs-required ─(jobs done | timeout)→ pod-deletion-required
                                     [drain-required if deletion disabled]
   pod-deletion-required ─(ok)→ pod-restart-required ; fail→ drain|failed
   drain-required ─(drain ok)→ pod-restart-required ; fail→ upgrade-failed
   pod-restart-required ─(pod recreated & ready)→ validation-required
                                     [uncordon-required | upgrade-done]
   validation-required ─(gate passes)→ uncordon-required | upgrade-done
   uncordon-required ─(uncordon ok)→ upgrade-done
   upgrade-failed ─(pod healthy again)→ uncordon-required | upgrade-done

``apply_state`` is stateless and idempotent: every decision derives from
the snapshot, and every transition is committed as a node label before any
further progress, so a crashed operator resumes mid-upgrade for free
(upgrade_state.go:68-72; SURVEY.md §5 "checkpoint/resume").

TPU-specific departure: node selection in upgrade-required is delegated to
a pluggable :class:`UpgradePlanner`. The default :class:`FlatPlanner`
reproduces the reference's per-node slot loop; the slice-aware planner in
``tpu_operator_libs.topology`` advances whole ICI domains atomically,
because draining one host of a multi-host TPU slice idles the entire slice
(SURVEY.md §5 "long-context / topology-coupled upgrade ordering").
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Optional, Protocol

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (topology
    # imports k8s.objects; planner imports this module's state types)
    from tpu_operator_libs.k8s.sharding import ShardElector
    from tpu_operator_libs.topology.multislice import MultisliceConstraint
    from tpu_operator_libs.topology.slice_topology import SliceTopology
    from tpu_operator_libs.upgrade.nudger import ReconcileNudger

from tpu_operator_libs.api.upgrade_policy import (
    DrainSpec,
    PodDeletionSpec,
    UpgradePolicySpec,
    WaitForCompletionSpec,
    scaled_value_from_int_or_percent,
)
from tpu_operator_libs.consts import (
    ABORTABLE_STATES,
    ALL_STATES,
    IN_PROGRESS_STATES,
    NODE_NAME_FIELD_SELECTOR_FMT,
    TRUE_STRING,
    TopologyKeys,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    K8sClient,
    NotFoundError,
)
from tpu_operator_libs.k8s.objects import DaemonSet, Node, Pod, PodPhase
from tpu_operator_libs.k8s.selectors import (
    parse_label_selector,
    selector_from_labels,
)
from tpu_operator_libs.upgrade.cordon_manager import CordonManager
from tpu_operator_libs.upgrade.drain_manager import (
    DrainConfiguration,
    DrainManager,
)
from tpu_operator_libs.upgrade.gate import EvictionGate
from tpu_operator_libs.upgrade.pod_manager import (
    PodDeletionFilter,
    PodManager,
    PodManagerConfig,
    RevisionHashError,
)
from tpu_operator_libs.upgrade.rollout_guard import (
    RolloutDecision,
    RolloutGuard,
)
from tpu_operator_libs.upgrade.safe_load_manager import SafeRuntimeLoadManager
from tpu_operator_libs.upgrade.state_provider import NodeUpgradeStateProvider
from tpu_operator_libs.upgrade.validation_manager import (
    NodeValidator,
    ValidationManager,
)
from tpu_operator_libs.upgrade.worker_pool import BoundedKeyedPool
from tpu_operator_libs.util import Clock, EventRecorder, Worker

logger = logging.getLogger(__name__)

#: A runtime pod restarted more than this many times while not ready is
#: considered failing (upgrade_state.go:966-978).
POD_RESTART_FAILURE_THRESHOLD = 10


class BuildStateError(RuntimeError):
    """build_state could not produce a consistent snapshot."""


@dataclass
class NodeUpgradeState:
    """A node, the runtime pod on it, and the owning DaemonSet
    (upgrade_state.go:40-49)."""

    node: Node
    runtime_pod: Pod
    runtime_daemon_set: Optional[DaemonSet]

    def is_orphaned(self) -> bool:
        return self.runtime_daemon_set is None


@dataclass
class ClusterUpgradeState:
    """Snapshot of the cluster bucketed by upgrade state
    (upgrade_state.go:51-62)."""

    node_states: dict[str, list[NodeUpgradeState]] = field(
        default_factory=dict)

    def bucket(self, state: UpgradeState | str) -> list[NodeUpgradeState]:
        return self.node_states.get(str(state), [])

    def all_nodes(self) -> list[Node]:
        """Every node in the snapshot, across all buckets."""
        return [ns.node for bucket in self.node_states.values()
                for ns in bucket]

    def topology(self) -> "SliceTopology":
        """The snapshot's :class:`SliceTopology`, built once and cached.

        One apply_state pass needs the grouping three times (planner,
        cluster status, metrics); at fleet scale rebuilding it per
        consumer tripled that slice of reconcile latency. The cache is
        safe because a snapshot's nodes are never mutated — a new pass
        builds a new state."""
        if getattr(self, "_topology", None) is None:
            from tpu_operator_libs.topology.slice_topology import (
                SliceTopology,
            )

            self._topology = SliceTopology.from_nodes(self.all_nodes())
        return self._topology


class UpgradePlanner(Protocol):
    """Chooses which upgrade-required nodes start upgrading this pass."""

    def plan(self, candidates: list[NodeUpgradeState], available: int,
             state: "ClusterUpgradeState") -> list[NodeUpgradeState]:
        """Return the subset of ``candidates`` to advance to
        cordon-required, at most ``available`` plus any already-cordoned
        nodes (which may proceed even without slots,
        upgrade_state.go:606-616)."""
        ...


class FlatPlanner:
    """Reference-parity planner: first-come order, one slot per node, with
    the manual-cordon override (upgrade_state.go:587-631)."""

    def plan(self, candidates: list[NodeUpgradeState], available: int,
             state: ClusterUpgradeState) -> list[NodeUpgradeState]:
        selected = []
        for ns in candidates:
            if available <= 0:
                if ns.node.is_unschedulable():
                    # already cordoned (manually or by a previous pass):
                    # proceeding does not reduce availability further.
                    selected.append(ns)
                continue
            selected.append(ns)
            available -= 1
        return selected


class _AuditingPlanner:
    """Outermost planner wrapper recording every per-candidate verdict
    into the manager's DecisionAudit: selected nodes get an ``admit``
    record (with their final rank — the LPT order after every inner
    filter), unselected ones a ``hold`` record with the blocking rule
    derived from the pass context (halt / canary cohort / exhausted
    budget / multislice budget / planner ordering). Installed only when
    observability is on; the inner chain's decisions are untouched."""

    def __init__(self, inner: UpgradePlanner,
                 manager: "ClusterUpgradeStateManager") -> None:
        self.inner = inner
        self._manager = manager

    def plan(self, candidates: list[NodeUpgradeState], available: int,
             state: "ClusterUpgradeState") -> list[NodeUpgradeState]:
        selected = self.inner.plan(candidates, available, state)
        manager = self._manager
        audit = manager._obs.audit
        chosen = {ns.node.metadata.name for ns in selected}
        for rank, ns in enumerate(selected):
            audit.record(
                "admit", ns.node.metadata.name, decision="admit",
                rule="planner",
                inputs={"rank": rank, "slots": available})
        # pass-wide context hoisted out of the per-candidate loop —
        # this loop is O(fleet) every pass
        rollout = manager._rollout
        deferred = manager.multislice_deferred_slices
        ranker = manager._cost_ranker
        ranker_holds = ranker.last_holds if ranker is not None else {}
        engine = manager._policy_engine
        policy_holds = engine.last_holds if engine is not None else {}
        uniform_rule = None
        if not rollout.halted and not rollout.canary_active \
                and not deferred and not ranker_holds \
                and not policy_holds:
            # the common regime: every held candidate blocks on the
            # same rule, so a steady pass with no admissions and an
            # unchanged (rule, candidate count) repeats facts the
            # dedup would drop one by one — skip the O(fleet) loop
            # outright (new arrivals still explain via the pass-level
            # budget record)
            uniform_rule = ("budget-exhausted" if available <= 0
                            else "planner-held")
            steady_key = (uniform_rule, len(candidates))
            if not selected \
                    and steady_key == manager._obs_last_steady_holds:
                return selected
            manager._obs_last_steady_holds = steady_key
        inputs = {"slots": available, "candidates": len(candidates)}
        if uniform_rule is not None:
            # one batched dedup sweep (C-speed comprehension + the
            # audit's changed-only filter) instead of a Python call
            # per held candidate
            audit.record_holds(
                [name for ns in candidates
                 if (name := ns.node.metadata.name) not in chosen],
                uniform_rule, inputs=inputs)
            return selected
        for ns in candidates:
            name = ns.node.metadata.name
            if name in chosen:
                continue
            if rollout.halted:
                rule = "rollout-halt"
            elif rollout.canary_active and name not in rollout.cohort:
                rule = "canary-cohort"
            elif name in ranker_holds:
                # the ranker already recorded the rich record (model/
                # class/prewarm arc); the shared rule dedups this one
                rule = ranker_holds[name][0]
            elif name in policy_holds:
                # the policy engine already recorded/audited the rich
                # hold (policy-deny/-error/-budget); dedup on its rule
                rule = policy_holds[name][0]
            elif deferred and manager._node_pool(ns.node) in deferred:
                rule = "multislice-budget"
            else:
                rule = ("budget-exhausted" if available <= 0
                        else "planner-held")
            audit.record_hold(name, rule, inputs=inputs)
        return selected


class ClusterUpgradeStateManager:
    """The state machine hub (upgrade_state.go:104-151)."""

    def __init__(self, client: K8sClient,
                 keys: Optional[UpgradeKeys] = None,
                 recorder: Optional[EventRecorder] = None,
                 clock: Optional[Clock] = None,
                 async_workers: bool = True,
                 provider: Optional[NodeUpgradeStateProvider] = None,
                 cordon_manager: Optional[CordonManager] = None,
                 drain_manager: Optional[DrainManager] = None,
                 pod_manager: Optional[PodManager] = None,
                 validation_manager: Optional[ValidationManager] = None,
                 safe_load_manager: Optional[SafeRuntimeLoadManager] = None,
                 planner: Optional[UpgradePlanner] = None,
                 sync_timeout: float = 10.0,
                 poll_interval: float = 1.0,
                 parallel_workers: int = 0,
                 incremental_reads: bool = True,
                 snapshot_mode: str = "auto",
                 nudger: Optional["ReconcileNudger"] = None) -> None:
        self.keys = keys or UpgradeKeys()
        # Same driver/domain family as the upgrade keys: marks the
        # slice-reconfiguration surface (spare reservations, remap
        # settle stamps, the degraded-slices DS record) the planners and
        # cluster_status consult for joint planning.
        self.topology_keys = TopologyKeys(driver=self.keys.driver,
                                          domain=self.keys.domain)
        self.client = client
        self.recorder = recorder
        self.clock = clock or Clock()
        self._async_workers = async_workers
        # Completion-driven wakeup seam (upgrade/nudger.py): threaded
        # into every manager that learns async outcomes or stamps
        # deadlines, so the reconcile loop is woken the moment the
        # outcome lands instead of on its next poll. None = the
        # reference's poll-paced behavior, bit for bit.
        self.nudger = nudger
        self.provider = provider or NodeUpgradeStateProvider(
            client, self.keys, recorder, self.clock,
            sync_timeout=sync_timeout, poll_interval=poll_interval)
        self.cordon_manager = cordon_manager or CordonManager(client)
        self.drain_manager = drain_manager or DrainManager(
            client, self.provider, recorder, self.clock,
            Worker(async_mode=async_workers), nudger=nudger)
        self.pod_manager = pod_manager or PodManager(
            client, self.provider, None, recorder, self.clock,
            Worker(async_mode=async_workers), nudger=nudger)
        self.validation_manager = validation_manager or ValidationManager(
            client, self.provider, "", recorder, self.clock, nudger=nudger)
        self.safe_load_manager = safe_load_manager or SafeRuntimeLoadManager(
            self.provider)
        # Canary/halt/rollback brain. Holds no durable state of its own
        # (quarantine + bake stamps live as DaemonSet annotations), so
        # rebuilding the manager after a crash loses nothing.
        self.rollout_guard = RolloutGuard(
            client, self.keys, recorder, self.clock,
            pod_failure_threshold=POD_RESTART_FAILURE_THRESHOLD)
        self.rollout_guard.nudger = nudger
        # The current pass's rollout decision (neutral outside
        # apply_state and whenever canary gating is disabled).
        self._rollout = RolloutDecision()
        # Explicit planner wins; otherwise policy.topology_mode selects
        # flat (reference parity) or slice-atomic planning per apply_state.
        self._explicit_planner = planner
        # Multislice-job awareness for the slice planner. Lives on the
        # manager (not rebuilt per pass) because its sticky-down
        # membership memory must survive across reconciles
        # (topology/multislice.py module docstring).
        self._multislice_constraint: Optional["MultisliceConstraint"] = None
        self._multislice_constraint_is_custom = False
        # ---- cost-aware predictive planning (upgrade/predictor.py) ----
        #: Online per-node/per-phase duration model; created on first
        #: use and kept across passes (its in-memory EWMAs are the
        #: learned state — the durable half lives on node annotations).
        self._predictor = None
        #: Persistent PredictiveWavePlanner wrapper (carries the fleet
        #: ETA of the most recent plan + window-deferral counters).
        self._predictive_planner = None
        #: Optional (kind, node, at, predicted_done) hook for every
        #: window admit/defer decision — the chaos harness's
        #: maintenance-window invariant feed.
        self.window_audit = None
        # ---- traffic-aware capacity budgets (upgrade/capacity.py) ----
        #: Persistent CapacityBudgetController; created on first use
        #: from a policy with capacityBudget.enable (its EWMAs are
        #: advisory in-memory state — every safety-relevant signal is
        #: re-derived from the live endpoints each pass).
        self._capacity = None
        #: node -> serving endpoints source installed via
        #: :meth:`with_serving_signal`; without one the controller
        #: fails open to the static budget exactly.
        self._capacity_source = None
        # ---- rollout preflight (upgrade/preflight.py) ----
        #: Persistent PreflightForecaster; created on first use from a
        #: policy with preflight.mode != "off". Pure read-side state:
        #: a forecast owns no durable bits, so crash-restart costs
        #: nothing but a recompute from the same snapshot inputs.
        self._preflight = None
        #: Forecast dict of the most recent preflight pass (None while
        #: preflight is off) — the cluster_status / explain / HTTP feed
        #: and the admission gate's evidence.
        self.last_preflight = None
        #: Optional diurnal-trace source (``utilization(now)``) handed
        #: to the forecaster — soaks and benches wire the same trace
        #: their serving sim replays so the forecast sweeps the real
        #: traffic shape.
        self.preflight_trace = None
        #: Optional crash-fuse guard for the forecast path (chaos
        #: harness seam; see PreflightForecaster.guard).
        self.preflight_guard = None
        # ---- traffic-class drain ordering + prewarm (handover.py) ----
        #: Persistent DisruptionCostRanker wrapper; created on first
        #: use from a policy declaring capacityBudget.trafficClasses
        #: (its last_holds feed the audit wrapper + explain chain).
        self._cost_ranker = None
        #: Persistent PrewarmCoordinator (stateless-durable — every
        #: pass re-derives reservations from node annotations).
        self._prewarm = None
        #: Deployment hooks installed via :meth:`with_prewarm_hooks`.
        self._prewarm_readiness = None
        self._prewarm_release = None
        #: Optional (kind, node, at, reason) hook for every mid-flight
        #: abort admission/completion — the chaos harness's
        #: abort-invariant feed (kind: "abort" | "aborted").
        self.abort_audit = None
        # ---- declarative policy engine + artifact DAG (policy/) ----
        #: Persistent PolicyEngine; created on first use from a policy
        #: carrying policyHooks (its registry also absorbs the Python
        #: constructor seams — see docs/policy-engine.md).
        self._policy_engine = None
        #: The ONE persistent PolicyEvictionGate wrapper (GateKeeper's
        #: set_gate identity-compares; a fresh wrapper per pass would
        #: release/re-park every parked node every reconcile).
        self._policy_gate = None
        #: Persistent PolicyAdmissionPlanner wrapper.
        self._policy_planner = None
        #: Persistent ArtifactDAGCoordinator; created on first use
        #: from a policy carrying artifactDAG (stateless-durable —
        #: every pass re-derives targets/stamps from cluster state).
        self._dag = None
        #: (namespace, runtime labels) of the most recent build_state —
        #: the DAG coordinator resolves artifact DaemonSets against
        #: the same scope the snapshot came from.
        self._last_namespace: Optional[str] = None
        self._last_runtime_labels: Optional[dict] = None
        # ---- journey tracing + decision audit (obs/) ----
        #: OperatorObservability installed via with_observability; None
        #: = reference behavior bit for bit (no tracer annotations, no
        #: audit records, no trace block in cluster_status).
        self._obs = None
        #: The transition-observer functions currently composed into
        #: the provider (predictor learning + journey tracer) — the
        #: identity signature _install_transition_observer compares to
        #: avoid re-wrapping every pass.
        self._observer_parts: tuple = ()
        #: The most recent build_state snapshot (any mode): the
        #: read-side truth explain() answers from without touching the
        #: cluster — safe under injected API faults.
        self.last_state: Optional[ClusterUpgradeState] = None
        #: (rule, candidate count) of the last uniform-rule hold sweep
        #: — _AuditingPlanner's steady-pass skip memo (reset implicitly
        #: by any change in either component).
        self._obs_last_steady_holds: "Optional[tuple]" = None

        #: DaemonSet inputs of the most recent build (uid -> DS): the
        #: budget-share ledger / oracle discovery surface.
        self._last_daemon_sets: dict[str, DaemonSet] = {}
        self._pod_deletion_enabled = False
        # vanished nodes already warned about (log-dedup only; carries
        # no state-machine meaning — apply_state stays snapshot-driven)
        self._warned_vanished: set[str] = set()
        self._validation_enabled = False
        # Bounded keyed pool for per-node bucket fan-out: the
        # independent process_* transitions of one bucket run on
        # parallel_workers threads, with a barrier per bucket, so every
        # pass still commits bucket-by-bucket in the reference's order.
        # Budget admission (planner.plan + the throttle math) stays
        # serialized at a single point regardless. 0 = serial (the
        # reference's semantics, and the default for tests).
        self._pool = (BoundedKeyedPool(max_workers=parallel_workers,
                                       name="bucket-pool")
                      if parallel_workers > 0 else None)
        # Incremental snapshot state for delta-capable clients
        # (CachedReadClient.delta_view): the previous pass's raw inputs,
        # patched per pass by the cache's change stream instead of
        # re-read wholesale — O(delta) reads per pass.
        self._incremental_reads = incremental_reads
        self._delta_view = None
        self._inputs_key: Optional[tuple[str, str, str]] = None
        self._inputs_ds: dict[str, DaemonSet] = {}
        self._inputs_pods: dict[tuple[str, str], Pod] = {}
        self._inputs_nodes: dict[str, Node] = {}
        # ---- O(partition) sharded reads (ISSUE 8) ----
        # With a sharded view AND a partition-capable cached client,
        # build_state stops post-filtering a full snapshot: the pod
        # cache only ever holds the owned partition (ingest filter),
        # and the fleet-level inputs (per-shard census, canary cohort
        # domain) are derived from NODE METADATA alone — maintained
        # incrementally below, so a steady-state pass costs
        # O(delta-in-partition), and the one O(fleet) object anywhere
        # is the node cache itself.
        self._partition_reads = False
        #: owned_shards() observed at the previous build — an ownership
        #: move invalidates the delta cursor and re-LISTs the pod cache
        #: so a takeover's first snapshot is bit-identical to the
        #: deposed owner's.
        self._last_owned_shards: Optional[frozenset] = None
        #: The fleet census store behind partition reads: shard ->
        #: {state-label: count} over the node cache's labels (no pod
        #: join) — the budget split's census and the last_shard_status
        #: feed. A node counts once it carries a state label —
        #: label-only is MORE restart-stable than the pod join (a
        #: mid-restart node keeps its label). ``snapshot_mode``
        #: selects the backing store: "columnar" keeps the census in
        #: parallel numpy arrays (bincount recounts, version-cached
        #: canary domain — see upgrade/columns.py), "dict" keeps the
        #: pre-columnar per-name dict semantics bit for bit, "parity"
        #: runs both and cross-checks every read, "auto" (default)
        #: picks columnar when numpy is importable. The env var
        #: TPU_OPERATOR_SNAPSHOT_MODE overrides at resolve time.
        #: The store's per-name decrement bookkeeping is the reason an
        #: incremental update never consults the previous snapshot's
        #: node object: apply_state commits transitions by mutating
        #: the snapshot nodes in place (the provider's write-back), so
        #: by the next build the "old" object already carries the new
        #: label and the delta would cancel itself out.
        self._snapshot_mode_cfg = snapshot_mode
        self._census_store = None
        #: Lifetime parity cross-checks run / failed ("parity" mode
        #: only) — the columnar_parity_checks_total metric feed.
        self.columnar_parity_checks = 0
        self.columnar_parity_mismatches = 0
        #: Names of nodes whose shard this replica owns (incrementally
        #: maintained alongside the census): the assembly-side
        #: ownership check and the partition completeness guard.
        self._owned_node_names: set[str] = set()
        #: Wall-clock cost of the most recent build_state (inputs +
        #: assembly) and the lifetime sum — the snapshot-build half of
        #: the shard bench's per-replica accounting.
        self.last_snapshot_build_seconds: Optional[float] = None
        self.snapshot_build_seconds_total = 0.0
        # deferral counters are bumped from pool threads too
        self._deferral_lock = threading.Lock()
        #: Lifetime count of per-node transitions deferred on a
        #: transient cluster error (see _defer_node_on_transient).
        self._transient_deferrals = 0
        #: Same, for the most recent apply_state pass — the
        #: CURRENT-flakiness signal callers requeue on (a swallowed
        #: deferral produces no watch event, so without a prompt
        #: requeue the retry would wait out the resync period). After
        #: a chained reconcile() this holds the FINAL pass's count,
        #: i.e. the deferrals still outstanding at chain exit — a
        #: deferral an earlier chain pass already retried successfully
        #: does not linger here.
        self.last_pass_deferrals = 0
        # ---- eager slot refill bookkeeping (see _eager_slot_refill) ----
        #: Nodes that reached DONE during the current pass — each one
        #: frees an in-flight slot the refill round may re-spend.
        self._pass_slots_freed = 0
        #: Lifetime refill rounds run / candidates admitted by them.
        self.eager_refills_total = 0
        self.eager_refill_admissions_total = 0
        #: Throttle observability for the most recent pass: in-progress
        #: count, slot budget and saturation — the gauge feed for
        #: metrics.observe_latency and the cluster_status "slots" block.
        self.last_pass_slots: Optional[dict] = None
        # ---- sharded control plane (k8s/sharding.py) ----
        #: Ownership view (ShardElector or StaticShardView). None = the
        #: single-owner reference semantics, bit for bit.
        self._shard_view: Optional["ShardElector"] = None
        #: The UNFILTERED snapshot of the most recent build (sharding
        #: only): fleet-wide truth for the rollout guard's cohort, the
        #: slice planner's topology grouping and the budget split —
        #: decisions that must be identical across replicas.
        self._last_full_state: Optional[ClusterUpgradeState] = None
        #: Fleet-wide per-shard census of the most recent build
        #: (sharding only): shard -> {"total": n, "byState": {...}} —
        #: the feed for metrics.observe_shards and cluster_status.
        self.last_shard_status: Optional[dict] = None
        #: Budget-share picture of the most recent pass (sharding
        #: only): global budget, entitlements, recorded shares, cap.
        self.last_budget_shares: Optional[dict] = None

    def with_sharding(
            self, view: Optional["ShardElector"],
    ) -> "ClusterUpgradeStateManager":
        """Install (or clear) the sharded-control-plane ownership view.

        With a view installed this replica's ``apply_state`` operates on
        an **ownership-filtered snapshot** (only nodes whose shard it
        owns), its durable writes are **fenced** (state provider AND
        cordon manager refuse writes outside the partition — a deposed
        replica's in-flight pass raises
        :class:`~tpu_operator_libs.k8s.sharding.ShardFencedError`
        instead of landing a split-brain write), and the global
        maxUnavailable budget is spent through **durable budget shares**
        on the runtime DaemonSet (see ``_sharded_unavailable_cap``).
        ``None`` restores single-owner semantics exactly.
        """
        self._shard_view = view
        fence = view.fence if view is not None else None
        with_fence = getattr(self.provider, "with_fence", None)
        if with_fence is not None:
            with_fence(fence)
        self.cordon_manager.with_fence(fence)
        # O(partition) reads: a partition-capable cached client gets
        # the view pushed down into its pod-cache ingest filter, and
        # build_state switches to the partition-delta path (owned pods
        # only + label-derived fleet census) instead of post-filtering
        # a full snapshot. A plain client keeps the PR 7 post-filter
        # semantics bit for bit.
        set_filter = getattr(self.client, "set_partition_filter", None)
        if set_filter is not None and self._incremental_reads:
            current = getattr(self.client, "partition_filter", None)
            if view is None:
                if current is not None:
                    set_filter(None)
                self._partition_reads = False
            else:
                if current is None or current.view is not view:
                    set_filter(view)
                self._partition_reads = True
            self._last_owned_shards = None
            self._census_store = (self._make_census_store(view.num_shards)
                                  if view is not None else None)
            self._owned_node_names = set()
            if self._delta_view is not None:
                self._delta_view.mark_full()
        if view is None:
            self._partition_reads = False
            self._last_full_state = None
            self.last_shard_status = None
            self.last_budget_shares = None
        return self

    @property
    def shard_view(self) -> Optional["ShardElector"]:
        return self._shard_view

    def _resolved_snapshot_mode(self) -> str:
        """Effective census-store mode: env override > constructor
        config; "auto" means columnar whenever numpy imports; any
        columnar-needing mode degrades to "dict" without numpy."""
        from tpu_operator_libs.upgrade import columns as _columns

        mode = os.environ.get("TPU_OPERATOR_SNAPSHOT_MODE", "") \
            or self._snapshot_mode_cfg
        if mode not in ("auto", "columnar", "dict", "parity"):
            mode = "auto"
        if mode == "auto":
            mode = "columnar" if _columns.HAVE_NUMPY else "dict"
        if mode in ("columnar", "parity") and not _columns.HAVE_NUMPY:
            mode = "dict"
        return mode

    @property
    def snapshot_build_mode(self) -> str:
        """"columnar" when the partition census runs on the columnar
        arrays (parity mode counts: its primary is columnar), "dict"
        otherwise — the metrics label value."""
        from tpu_operator_libs.upgrade.columns import (
            CensusColumns,
            ParityCensus,
        )

        store = self._census_store
        if isinstance(store, (CensusColumns, ParityCensus)):
            return "columnar"
        return "dict"

    def _make_census_store(self, num_shards: int) -> "object":
        from tpu_operator_libs.upgrade.columns import (
            CensusColumns,
            DictCensus,
            ParityCensus,
        )

        mode = self._resolved_snapshot_mode()
        if mode == "columnar":
            return CensusColumns(num_shards)
        if mode == "parity":
            def _warn(site: str) -> None:
                logger.warning(
                    "columnar census parity mismatch at %s "
                    "(answering from the columnar primary)", site)

            return ParityCensus(CensusColumns(num_shards),
                                DictCensus(num_shards),
                                on_mismatch=_warn)
        return DictCensus(num_shards)

    def _record_parity_counters(self) -> None:
        """Roll the parity wrapper's counters up into the manager-level
        lifetime counters the metrics layer scrapes."""
        store = self._census_store
        checks = getattr(store, "checks", None)
        if checks is not None:
            self.columnar_parity_checks = checks
            self.columnar_parity_mismatches = store.mismatches

    def _census_entry(self, name: str) -> "Optional[tuple[int, str]]":
        """(shard, state-label) the census records for ``name`` (any
        backing store), or None outside partition-reads mode."""
        store = self._census_store
        if store is None:
            return None
        return store.entry(name)

    def with_nudger(
            self, nudger: Optional["ReconcileNudger"],
    ) -> "ClusterUpgradeStateManager":
        """Install (or clear) the completion-wakeup seam on this manager
        AND every node-action manager it currently holds. Use after
        construction when the nudger is built later than the manager
        (e.g. the OperatorManager wires it to the controller at
        start)."""
        self.nudger = nudger
        self.drain_manager.nudger = nudger
        self.pod_manager.nudger = nudger
        self.validation_manager.nudger = nudger
        self.rollout_guard.nudger = nudger
        if self._capacity is not None:
            self._capacity.nudger = nudger
        return self

    def with_observability(
            self, obs: "Optional[object]",
    ) -> "ClusterUpgradeStateManager":
        """Install (or clear) the journey-tracer + decision-audit
        bundle (:class:`tpu_operator_libs.obs.OperatorObservability`).

        With it installed: every durable transition grows a span in the
        node's journey (trace-id annotation riding the same merge
        patch, so journeys survive crashes and takeovers), every
        admission/hold/defer/abort decision lands in the bounded audit
        ring, ``cluster_status`` gains a ``"trace"`` block, and
        :meth:`explain` answers from the ring + the last snapshot.
        ``None`` restores reference behavior exactly."""
        self._obs = obs
        self._install_transition_observer(
            predictor_active=self._observer_parts[:1] == (
                getattr(self._predictor, "observe_transition", None),))
        return self

    @property
    def observability(self) -> "Optional[object]":
        return self._obs

    def _install_transition_observer(self,
                                     predictor_active: bool) -> None:
        """(Re)compose the provider's single ``transition_observer``
        slot from the active parts: the predictor's learning observer
        (policy-driven, first — its stamps are load-bearing) and the
        journey tracer (whenever obs is installed). Annotation updates
        merge with first-writer-wins on collision (the parts use
        disjoint keys); a part failing never blocks the others or the
        commit."""
        parts = []
        if predictor_active and self._predictor is not None:
            parts.append(self._predictor.observe_transition)
        if self._obs is not None:
            parts.append(self._obs.tracer.observe_transition)
        desired = tuple(parts)
        if desired == self._observer_parts and (
                desired or getattr(self.provider, "transition_observer",
                                   None) is None):
            return
        self._observer_parts = desired
        if not hasattr(self.provider, "transition_observer"):
            return
        if not desired:
            self.provider.transition_observer = None
        elif len(desired) == 1:
            self.provider.transition_observer = desired[0]
        else:
            assert len(desired) == 2, "compose supports two observers"

            def composed(node, old_label, new_label,
                         _first=desired[0], _second=desired[1]):
                # two-part fast path (predictor + tracer is the only
                # composition today): no merge allocation unless BOTH
                # return updates — the common intermediate transition
                # returns None from both, and this runs inside the
                # commit path for every durable transition
                try:
                    first = _first(node, old_label, new_label)
                except Exception:  # noqa: BLE001 — one observer
                    # failing must not starve the other
                    logger.warning(
                        "transition observer %r failed for node %s "
                        "(%r -> %r); continuing", _first,
                        node.metadata.name, old_label, new_label,
                        exc_info=True)
                    first = None
                try:
                    second = _second(node, old_label, new_label)
                except Exception:  # noqa: BLE001
                    logger.warning(
                        "transition observer %r failed for node %s "
                        "(%r -> %r); continuing", _second,
                        node.metadata.name, old_label, new_label,
                        exc_info=True)
                    second = None
                if not second:
                    return first
                if not first:
                    return second
                # first writer wins on collision (disjoint keys today)
                merged = dict(second)
                merged.update(first)
                return merged

            self.provider.transition_observer = composed

    @property
    def planner(self) -> UpgradePlanner:
        """The explicitly-set planner, or the flat default. Assigning here
        overrides policy-driven selection permanently."""
        return self._explicit_planner or FlatPlanner()

    @planner.setter
    def planner(self, value: Optional[UpgradePlanner]) -> None:
        self._explicit_planner = value

    # ------------------------------------------------------------------
    # options (upgrade_state.go:155-186)
    # ------------------------------------------------------------------
    def with_pod_deletion_enabled(
            self, deletion_filter: PodDeletionFilter,
            eviction_gate: Optional[EvictionGate] = None,
    ) -> "ClusterUpgradeStateManager":
        if deletion_filter is None:
            logger.warning("cannot enable pod deletion: filter is None")
            return self
        if eviction_gate is None:
            # Preserve a gate installed earlier via with_eviction_gate —
            # rebuilding the PodManager must not drop it.
            eviction_gate = self.pod_manager.eviction_gate
        self.pod_manager = PodManager(
            self.client, self.provider, deletion_filter, self.recorder,
            self.clock, Worker(async_mode=self._async_workers),
            eviction_gate=eviction_gate, nudger=self.nudger)
        if eviction_gate is not None:
            # The drain fallback must honor the same gate, or a failed
            # pod deletion would evict the workload anyway.
            self.drain_manager.set_eviction_gate(eviction_gate)
        self._pod_deletion_enabled = True
        return self

    def with_eviction_gate(
            self, gate: Optional[EvictionGate],
    ) -> "ClusterUpgradeStateManager":
        """Install an eviction gate on both the pod-deletion and drain
        paths without enabling the pod-deletion state."""
        self.pod_manager.set_eviction_gate(gate)
        self.drain_manager.set_eviction_gate(gate)
        return self

    def with_validation_enabled(
            self, pod_selector: str = "",
            extra_validator: Optional[NodeValidator] = None,
    ) -> "ClusterUpgradeStateManager":
        if not pod_selector and extra_validator is None:
            logger.warning("cannot enable validation: no selector or "
                           "validator provided")
            return self
        self.validation_manager = ValidationManager(
            self.client, self.provider, pod_selector, self.recorder,
            self.clock, extra_validator, nudger=self.nudger)
        self._validation_enabled = True
        return self

    @property
    def is_pod_deletion_enabled(self) -> bool:
        return self._pod_deletion_enabled

    @property
    def is_validation_enabled(self) -> bool:
        return self._validation_enabled

    @property
    def _policy_validation_active(self) -> bool:
        """True while the policy engine's validation.verdict program
        or the artifact-DAG completion gate is installed: restarted
        nodes must route through validation-required so the seam can
        judge (or park) them — with neither, the reference's
        skip-validation shortcut applies bit for bit."""
        return self.validation_manager.policy_validator is not None

    # ------------------------------------------------------------------
    # build_state (upgrade_state.go:214-355)
    # ------------------------------------------------------------------
    def build_state(self, namespace: str,
                    runtime_labels: dict[str, str],
                    node_selector: str = "") -> ClusterUpgradeState:
        """Snapshot runtime DaemonSets + pods + nodes into state buckets.

        Reads go one of three ways: a plain client is re-listed
        wholesale every pass (reference semantics — but one bulk LIST
        instead of the reference's GET per pod, upgrade_state.go:285); a
        delta-capable client (CachedReadClient) is consulted only for
        the objects its watch stream marked dirty since the previous
        pass, the prior inputs are patched in place, and only a resync
        (first pass, watch overflow relist, selector change) falls back
        to the full re-read — per-pass read cost O(delta), not
        O(cluster); and a SHARDED manager over a partition-capable
        cached client reads only its owned partition's pods (the cache
        never held the rest), with the fleet-level census derived from
        node labels alone — O(delta-in-partition) per steady-state
        pass. All paths feed the same assembly, so the snapshot
        semantics are byte-identical (pinned by the mock-parity and
        partition-parity tests).

        ``node_selector`` (usually ``policy.node_selector``, threaded
        by :meth:`reconcile`) scopes the node LIST to the managed node
        pool — unmanaged pools sharing the cluster are neither read
        nor acted on.
        """
        import time as _time

        started = _time.perf_counter()
        reset_memo = getattr(self.pod_manager, "reset_revision_cache", None)
        if reset_memo is not None:
            # the revision oracle's memo is per-snapshot: within one
            # pass a DaemonSet's newest revision is immutable
            reset_memo()
        selector = selector_from_labels(runtime_labels)
        # scope memo for the artifact-DAG coordinator: artifact
        # DaemonSets resolve against the same namespace the snapshot
        # came from
        self._last_namespace = namespace
        self._last_runtime_labels = dict(runtime_labels)
        daemon_sets, pods, nodes_by_name = self._snapshot_inputs(
            namespace, selector, node_selector)
        # the ledger/oracle DaemonSet set of this snapshot (budget
        # shares, rollout bookkeeping) — present even when every pod of
        # a DS is mid-restart, unlike a pod-derived discovery
        self._last_daemon_sets = daemon_sets
        state = self._assemble_state(daemon_sets, pods, nodes_by_name)
        self.last_snapshot_build_seconds = _time.perf_counter() - started
        self.snapshot_build_seconds_total += self.last_snapshot_build_seconds
        # retained for read-side consumers (explain, status probes):
        # a reference, not a copy — apply_state mutates it in place,
        # which is exactly the freshness explain wants
        self.last_state = state
        return state

    def _full_inputs(self, namespace: str, selector: str,
                     node_selector: str = "") -> tuple[
            dict[str, DaemonSet], list[Pod], dict[str, Node]]:
        daemon_sets = {ds.metadata.uid: ds
                       for ds in self.client.list_daemon_sets(
                           namespace, selector)}
        pods = self.client.list_pods(namespace=namespace,
                                     label_selector=selector)
        nodes_by_name = {n.metadata.name: n
                         for n in self.client.list_nodes(node_selector)}
        return daemon_sets, pods, nodes_by_name

    def _snapshot_inputs(self, namespace: str, selector: str,
                         node_selector: str = "") -> tuple[
            dict[str, DaemonSet], list[Pod], dict[str, Node]]:
        factory = (getattr(self.client, "delta_view", None)
                   if self._incremental_reads else None)
        if factory is None:
            return self._full_inputs(namespace, selector, node_selector)
        if self._delta_view is None:
            self._delta_view = factory()
        partition = self._partition_reads and self._shard_view is not None
        if partition:
            owned = frozenset(self._shard_view.owned_shards())
            if owned != self._last_owned_shards:
                # Shard acquisition/handover: events for newly-owned
                # pods were dropped at ingest before the move — only a
                # targeted re-LIST of the pod cache repairs that, and
                # the delta cursor is invalidated so the next build
                # cannot patch a snapshot whose partition boundary
                # moved under it. This is what keeps a takeover's first
                # snapshot bit-identical to the deposed owner's.
                refresh = getattr(self.client, "refresh_partition", None)
                if refresh is not None:
                    refresh()
                self._delta_view.mark_full()
                self._last_owned_shards = owned
        delta = self._delta_view.poll()
        key = (namespace, selector, node_selector)
        try:
            if delta.full or self._inputs_key != key:
                ds, pods, nodes = self._full_inputs(namespace, selector,
                                                    node_selector)
                self._inputs_key = key
                self._inputs_ds = ds
                self._inputs_pods = {
                    (p.metadata.namespace, p.metadata.name): p
                    for p in pods}
                self._inputs_nodes = nodes
                if partition:
                    self._rebuild_fleet_census()
                return ds, pods, nodes
            if delta.daemon_sets:
                self._inputs_ds = {
                    ds.metadata.uid: ds
                    for ds in self.client.list_daemon_sets(
                        namespace, selector)}
            if delta.pods:
                label_match = parse_label_selector(selector)
                for pod_key in delta.pods:
                    if pod_key[0] != namespace:
                        continue
                    try:
                        pod = self.client.get_pod(*pod_key)
                    except NotFoundError:
                        pod = None
                    if pod is None or not label_match(pod.metadata.labels):
                        self._inputs_pods.pop(pod_key, None)
                    else:
                        self._inputs_pods[pod_key] = pod
            if delta.nodes:
                node_match = parse_label_selector(node_selector)
                for name in delta.nodes:
                    try:
                        node = self.client.get_node(name)
                    except NotFoundError:
                        node = None
                    if node is not None \
                            and not node_match(node.metadata.labels):
                        # left the managed pool: same as deleted, for
                        # this manager's purposes
                        node = None
                    if node is None:
                        self._inputs_nodes.pop(name, None)
                    else:
                        self._inputs_nodes[name] = node
                    if partition:
                        self._census_update(name, node)
        except Exception:
            # the delta was consumed but not fully applied: without
            # this the lost entries would leave the snapshot stale
            # FOREVER. Force a full rebuild on the next pass (which
            # also rebuilds the fleet census from scratch).
            self._delta_view.mark_full()
            raise
        return (self._inputs_ds, list(self._inputs_pods.values()),
                self._inputs_nodes)

    # ------------------------------------------------------------------
    # fleet census over node labels (partition-reads mode)
    # ------------------------------------------------------------------
    def _node_pool(self, node: Node) -> str:
        from tpu_operator_libs.consts import GKE_NODEPOOL_LABEL

        return node.metadata.labels.get(GKE_NODEPOOL_LABEL, "")

    def _rebuild_fleet_census(self) -> None:
        """Recompute the label-derived per-shard census and the
        owned-node set from the full node input map. O(fleet) — runs
        only on a full resync or an ownership move; steady-state passes
        maintain both incrementally via :meth:`_census_update`. The
        census itself lives in the mode-selected store (columnar
        arrays or the dict twin — see ``_make_census_store``)."""
        view = self._shard_view
        owned = view.owned_shards()
        if self._census_store is None:
            self._census_store = self._make_census_store(view.num_shards)
        owned_names: set[str] = set()
        state_label = self.keys.state_label
        skip_label = self.keys.skip_label
        ring = view.ring
        rows: list[tuple[str, int, str, bool, str]] = []
        for name, node in self._inputs_nodes.items():
            pool = self._node_pool(node)
            shard = ring.shard_for(name, pool)
            if shard in owned:
                owned_names.add(name)
            labels = node.metadata.labels
            rows.append((name, shard, labels.get(state_label, ""),
                         labels.get(skip_label) == TRUE_STRING, pool))
        self._census_store.rebuild(rows)
        self._owned_node_names = owned_names

    def _census_update(self, name: str, new: Optional[Node]) -> None:
        """Apply one node delta to the incremental census + owned set.
        The decrement comes from the store's recorded entry, so it is
        immune to in-place mutation of the previous snapshot's node
        objects."""
        view = self._shard_view
        store = self._census_store
        if store is None:
            store = self._census_store = \
                self._make_census_store(view.num_shards)
        if new is None:
            store.remove(name)
            self._owned_node_names.discard(name)
            return
        pool = self._node_pool(new)
        shard = view.ring.shard_for(name, pool)
        labels = new.metadata.labels
        store.update(name, shard,
                     labels.get(self.keys.state_label, ""),
                     labels.get(self.keys.skip_label) == TRUE_STRING,
                     pool)
        if shard in view.owned_shards():
            self._owned_node_names.add(name)
        else:
            self._owned_node_names.discard(name)

    def _assemble_state(self, daemon_sets: dict[str, DaemonSet],
                        pods: list[Pod],
                        nodes_by_name: dict[str, Node]) -> ClusterUpgradeState:
        """Bucket the raw snapshot inputs — pure CPU, no cluster reads."""
        state = ClusterUpgradeState()
        # Deliberate delta from the reference, which errors the whole
        # BuildState on a vanished node (upgrade_state.go:285 error
        # path): a node deleted mid-upgrade (scale-down, repair) leaves
        # its runtime pod behind until pod GC catches up, and aborting
        # the snapshot would stall the ENTIRE fleet's upgrade for that
        # window. The stranded pods are excluded HERE, before the
        # desired-count completeness guard below — the DS controller has
        # already dropped its desired count for the gone node, so
        # counting the lingering pod would otherwise fail the guard for
        # the whole GC window.
        partition = (self._partition_reads and self._shard_view
                     is not None)
        if partition:
            # Exact ownership boundary: the ingest filter is fail-open
            # (it keeps a pod whose node it cannot resolve yet), so the
            # authoritative check runs here against the fleet node map.
            # O(partition) memoized ring lookups.
            owned = self._owned_node_names
            pods = [p for p in pods
                    if not p.spec.node_name or p.spec.node_name in owned]
        live_pods = []
        stranded_by_uid: dict[str, int] = {}
        vanished_now: set[str] = set()
        for pod in pods:
            if pod.spec.node_name and pod.spec.node_name not in nodes_by_name:
                # WARNING once per vanished node, DEBUG on the repeats —
                # the condition persists for the whole pod-GC window and
                # a per-pass warning would just be noise. vanished_now
                # covers a second pod of the same node within this pass.
                repeat = (pod.spec.node_name in self._warned_vanished
                          or pod.spec.node_name in vanished_now)
                vanished_now.add(pod.spec.node_name)
                level = logging.DEBUG if repeat else logging.WARNING
                logger.log(
                    level,
                    "node %r (runtime pod %s) no longer exists; "
                    "skipping until pod GC removes the pod",
                    pod.spec.node_name, pod.name)
                owner = pod.controller_owner()
                if owner is not None:
                    stranded_by_uid[owner.uid] = (
                        stranded_by_uid.get(owner.uid, 0) + 1)
                continue
            live_pods.append(pod)
        pods = live_pods
        # forget healed entries so a future recurrence warns again
        self._warned_vanished = vanished_now

        filtered: list[tuple[Pod, Optional[DaemonSet]]] = []
        for ds in daemon_sets.values():
            ds_pods = [p for p in pods
                       if not p.is_orphaned()
                       and p.controller_owner().uid == ds.metadata.uid]
            stranded = stranded_by_uid.get(ds.metadata.uid, 0)
            # Completeness guard (upgrade_state.go:243-246), vanished-
            # node aware: after a node deletion the DS controller may
            # not yet have dropped its desired count, so the lagging
            # count (live + stranded) is accepted alongside the synced
            # one. Deliberate tradeoff: while BOTH a stranded pod and an
            # in-flight recreation exist, the lagging interpretation can
            # mask the recreation and the throttle can overshoot by at
            # most the stranded-pod count for one pass — bounded,
            # transient, and self-correcting, versus the reference's
            # answer of stalling the ENTIRE fleet for the whole GC
            # window. Anything outside these two counts means genuinely
            # unscheduled pods — refuse to act.
            if ds.status.desired_number_scheduled not in (
                    len(ds_pods), len(ds_pods) + stranded):
                if partition:
                    # Partition-reads: the desired count is fleet-wide
                    # but the pod snapshot is partition-scoped, so the
                    # raw guard always "fails" — the real question is
                    # whether OUR partition has holes. O(partition) set
                    # difference against the owned-node set, same
                    # semantics as the post-filter mode's fleet scan.
                    covered = {p.spec.node_name for p in ds_pods
                               if p.spec.node_name}
                    if self._owned_node_names - covered:
                        raise BuildStateError(
                            f"runtime DaemonSet {ds.metadata.name} "
                            f"should not have unscheduled pods")
                    logger.debug(
                        "runtime DaemonSet %s has pod-restart holes "
                        "outside this replica's partition; proceeding",
                        ds.metadata.name)
                elif self._shard_view is not None and \
                        self._partition_is_complete(ds_pods, nodes_by_name):
                    # Sharded control plane: the missing pods are all on
                    # OTHER replicas' partitions — their owners are
                    # mid-pod-restart, which is the steady state of a
                    # concurrent rollout. A fleet-wide abort here would
                    # serialize the replicas behind whichever one
                    # deleted pods first this tick (tick-order
                    # starvation); our own partition is complete, so
                    # the snapshot is safe for every decision we own.
                    logger.debug(
                        "runtime DaemonSet %s has pod-restart holes "
                        "outside this replica's partition; proceeding",
                        ds.metadata.name)
                else:
                    raise BuildStateError(
                        f"runtime DaemonSet {ds.metadata.name} should "
                        f"not have unscheduled pods")
            filtered.extend((p, ds) for p in ds_pods)
        filtered.extend((p, None) for p in pods if p.is_orphaned())

        for pod, ds in filtered:
            if not pod.spec.node_name:
                # unscheduled pod: Pending is the normal transient (pod
                # recreation in flight); any other phase with no node is
                # abnormal and must be loud — but it is not a "vanished
                # node" (those were excluded above), so no misleading
                # pod-GC diagnosis
                level = (logging.INFO
                         if pod.status.phase == PodPhase.PENDING
                         else logging.WARNING)
                logger.log(level, "runtime pod %s (phase %s) has no "
                           "node, skipping", pod.name, pod.status.phase)
                continue
            node = nodes_by_name[pod.spec.node_name]
            node_state = NodeUpgradeState(
                node=node, runtime_pod=pod, runtime_daemon_set=ds)
            label = node.metadata.labels.get(self.keys.state_label, "")
            state.node_states.setdefault(label, []).append(node_state)
        if partition:
            # Already partition-scoped: no post-filter pass. The fleet
            # picture (census, ownership) comes from the incrementally
            # maintained node-label census; there is no full snapshot
            # to retain — fleet-level decisions consume the census and
            # the node map, never a fleet-wide pod join.
            self._last_full_state = None
            view = self._shard_view
            census = self._census_store.per_shard()
            self.last_shard_status = {
                "owned": sorted(view.owned_shards()),
                "numShards": view.num_shards,
                "perShard": {
                    shard: {"total": sum(cell.values()),
                            "byState": dict(cell)}
                    for shard, cell in sorted(census.items())},
            }
            self._record_parity_counters()
            return state
        if self._shard_view is not None:
            return self._filter_owned_partition(state, nodes_by_name)
        return state

    def _partition_is_complete(self, ds_pods: "list[Pod]",
                               nodes_by_name: "dict[str, Node]") -> bool:
        """True when every node LACKING a pod of this DaemonSet lies
        outside this replica's partition — the sharded relaxation of
        the completeness guard (holes in OUR partition keep the
        reference's refuse-to-act semantics, bit for bit)."""
        from tpu_operator_libs.consts import GKE_NODEPOOL_LABEL

        covered = {pod.spec.node_name for pod in ds_pods
                   if pod.spec.node_name}
        view = self._shard_view
        return not any(
            view.owns(name,
                      node.metadata.labels.get(GKE_NODEPOOL_LABEL, ""))
            for name, node in nodes_by_name.items()
            if name not in covered)

    def _filter_owned_partition(
            self, state: ClusterUpgradeState,
            nodes_by_name: "dict[str, Node]") -> ClusterUpgradeState:
        """Ownership filter: keep only nodes whose shard this replica
        owns, while retaining the full snapshot (fleet-wide truth for
        the rollout cohort, slice planning and the budget split) and a
        per-shard census for metrics/status.

        The census counts a node as managed when it carries a runtime
        pod OR an upgrade-state label: a node whose pod is mid-restart
        (deleted, recreation in flight) falls out of the pod snapshot
        but must NOT fall out of the budget denominator — with several
        replicas restarting pods concurrently, a pod-only census
        shrinks and grows every tick and the budget entitlements flap
        with it (observed as alternating-tick cap oscillation in the
        shard bench)."""
        from tpu_operator_libs.consts import GKE_NODEPOOL_LABEL

        view = self._shard_view
        self._last_full_state = state
        owned = view.owned_shards()
        census: dict[int, dict] = {
            shard: {"total": 0, "byState": {}}
            for shard in range(view.num_shards)}
        covered: set[str] = set()
        filtered = ClusterUpgradeState()
        for label, bucket in state.node_states.items():
            for ns in bucket:
                covered.add(ns.node.metadata.name)
                shard = view.ring.shard_for(
                    ns.node.metadata.name,
                    ns.node.metadata.labels.get(GKE_NODEPOOL_LABEL, ""))
                cell = census[shard]
                cell["total"] += 1
                key = label or "unknown"
                cell["byState"][key] = cell["byState"].get(key, 0) + 1
                if shard in owned:
                    filtered.node_states.setdefault(label, []).append(ns)
        for name, node in nodes_by_name.items():
            if name in covered:
                continue
            label = node.metadata.labels.get(self.keys.state_label, "")
            if not label:
                continue  # no pod, never managed: not fleet capacity
            shard = view.ring.shard_for(
                name, node.metadata.labels.get(GKE_NODEPOOL_LABEL, ""))
            cell = census[shard]
            cell["total"] += 1
            cell["byState"][label] = cell["byState"].get(label, 0) + 1
        self.last_shard_status = {
            "owned": sorted(owned),
            "numShards": view.num_shards,
            "perShard": census,
        }
        return filtered

    def _sharded_canary_context(self, state: ClusterUpgradeState,
                                policy: UpgradePolicySpec) -> "object":
        """The rollout guard's fleet-wide cohort domain under partition
        reads, derived WITHOUT a fleet pod join.

        With ``policy.node_selector`` set, the selector-scoped node map
        IS the managed fleet — every replica derives the identical,
        day-zero-complete cohort domain from node metadata alone (the
        recommended configuration for sharded canary fleets). Without
        one, fleet-wide membership is only visible once a node carries
        a state label, so the domain is the labeled fleet plus this
        partition's podded nodes; replicas converge on the same domain
        after each partition's first triage pass, and the per-shard
        attestation stamps keep a transiently narrower domain from
        opening the fleet waves early (a shard owner only attests
        members it can verify against its own pods)."""
        from tpu_operator_libs.upgrade.rollout_guard import (
            ShardedCanaryContext,
        )

        skip = self.keys.skip_label
        store = self._census_store if self._partition_reads else None
        if store is not None:
            # Columnar fast path: the cohort domain comes straight from
            # the census store's version-cached eligible set — a steady
            # pass whose label transitions stay within labeled states
            # reuses the previous sorted list outright, instead of the
            # former O(fleet) label walk per pass. Only the partition's
            # podded augmentation (no-selector mode) is recomputed, and
            # that is O(partition).
            if policy.node_selector:
                return ShardedCanaryContext(
                    view=self._shard_view,
                    eligible=store.eligible(labeled_only=False))
            eligible = dict(store.eligible(labeled_only=True))
            for bucket in state.node_states.values():
                for ns in bucket:
                    node = ns.node
                    if node.metadata.labels.get(skip) != TRUE_STRING:
                        eligible[node.metadata.name] = \
                            self._node_pool(node)
            return ShardedCanaryContext(
                view=self._shard_view,
                eligible=sorted(eligible.items()))
        eligible = {}
        if policy.node_selector:
            for name, node in self._inputs_nodes.items():
                if node.metadata.labels.get(skip) != TRUE_STRING:
                    eligible[name] = self._node_pool(node)
        else:
            state_label = self.keys.state_label
            for name, node in self._inputs_nodes.items():
                if node.metadata.labels.get(skip) == TRUE_STRING:
                    continue
                if node.metadata.labels.get(state_label, ""):
                    eligible[name] = self._node_pool(node)
            for bucket in state.node_states.values():
                for ns in bucket:
                    node = ns.node
                    if node.metadata.labels.get(skip) != TRUE_STRING:
                        eligible[node.metadata.name] = \
                            self._node_pool(node)
        return ShardedCanaryContext(
            view=self._shard_view,
            eligible=sorted(eligible.items()))

    def _sharded_budget_caps(
            self, policy: UpgradePolicySpec,
            capacity: "object" = None) -> tuple[int, int]:
        """The partition's (maxUnavailable, maxParallel) caps under the
        durable budget-share protocol.

        The GLOBAL budget ``B`` is the policy scaled against the FULL
        fleet; ``split_budget`` partitions it deterministically across
        shards proportional to their node counts (sum == B exactly, so
        every replica computing the same split cannot jointly overdraw).
        The durable half closes the crash/skew holes: each owned
        shard's share is recorded under its own annotation key on the
        runtime DaemonSet (distinct keys — concurrent owners' merge
        patches compose), and the spend rule is asymmetric:

        - a DECREASE (fleet shrank, shard shrank) takes effect
          immediately — ``min(entitlement, recorded)``;
        - an INCREASE only takes effect one pass AFTER it was recorded
          and read back from the snapshot, so by the time any replica
          spends against a larger share, every replica's snapshot shows
          it and the global clamp below applies to the same numbers.

        The clamp is the takeover/skew backstop: if the recorded shares
        of ALL shards ever sum past B (two replicas mid-disagreement
        about the fleet size), this replica reduces its own cap to what
        provably fits under B next to everyone else's recorded claims —
        the conservative resolution that needs no coordination.
        """
        from tpu_operator_libs.k8s.sharding import (
            ShardBudgetLedger,
            ledger_spend_cap,
            split_budget,
        )

        view = self._shard_view
        owned = view.owned_shards()
        # the stable managed-node census (pods + mid-restart label
        # holders) computed by _filter_owned_partition for this build
        counts = {shard: cell["total"] for shard, cell in
                  self.last_shard_status["perShard"].items()}
        fleet_total = sum(counts.values())
        global_budget = fleet_total
        if policy.max_unavailable is not None:
            global_budget = scaled_value_from_int_or_percent(
                policy.max_unavailable, fleet_total, round_up=True)
        if capacity is not None:
            # traffic-aware modulation of the GLOBAL budget, before the
            # deterministic split: every replica reading the same
            # fleet-level serving signal derives the same effective B,
            # and the share ledger's decrease-now/increase-next-pass
            # rule handles the per-pass movement exactly like a fleet
            # resize would
            global_budget = capacity.effective_budget(global_budget)
        entitled = split_budget(global_budget, counts)

        # the ledger DaemonSet: deterministically the first runtime DS
        # (sorted by namespace/name) — every replica LISTs the same
        # selector, so every replica picks the same one. Taken from the
        # snapshot's DS inputs, not from the pod join: a DS whose pods
        # are all mid-restart (or all on other partitions) must still
        # carry the ledger.
        ledger = ShardBudgetLedger(self.keys)
        ledger_ds = None
        seen: dict[str, DaemonSet] = {}
        for ds in self._last_daemon_sets.values():
            meta = ds.metadata
            seen[f"{meta.namespace}/{meta.name}"] = ds
        if seen:
            ledger_ds = seen[min(seen)]
        recorded = (ledger.shares_from(ledger_ds.metadata.annotations)
                    if ledger_ds is not None else {})

        # spend rule (decrease-immediate / increase-next-pass) + global
        # clamp, shared with the federation ledger (sharding.py)
        cap = ledger_spend_cap(owned, entitled, recorded, global_budget)

        # record our owned shards' entitlements when they changed (ONE
        # merge patch, disjoint keys per shard — crash-atomic, and
        # concurrent replicas never touch each other's keys)
        stale = {shard: entitled[shard] for shard in owned
                 if recorded.get(shard) != entitled[shard]}
        if fleet_total <= 0:
            # Bootstrap guard (label-derived census): before any node
            # carries a state label the census is empty and every
            # entitlement is zero — recording those zeros would make
            # the real first-pass shares an "increase" and cost every
            # replica one idle pass under the increase-next-pass rule.
            # An unestablished ledger already spends conservatively
            # (unrecorded shares count as entitlement on both sides of
            # the clamp), so stamp nothing until the census exists.
            stale = {}
        if stale and ledger_ds is not None:
            try:
                self.client.patch_daemon_set_annotations(
                    ledger_ds.metadata.namespace,
                    ledger_ds.metadata.name,
                    {ledger.annotation_key(shard): str(share)
                     for shard, share in stale.items()})
            except (ApiServerError, ConflictError, NotFoundError) as exc:
                # transient: spend against the OLD recorded shares this
                # pass (conservative) and retry the stamp next pass
                logger.warning("budget-share stamp deferred on "
                               "transient error: %s", exc)

        max_parallel = policy.max_parallel_upgrades
        if max_parallel > 0:
            parallel_split = split_budget(max_parallel, counts)
            max_parallel = sum(parallel_split[s] for s in owned)
            if max_parallel == 0:
                # 0 means UNLIMITED to the throttle; a shard whose
                # parallel share rounded to zero must spend nothing
                max_parallel = -1
        self.last_budget_shares = {
            "globalBudget": global_budget,
            "entitled": {str(s): entitled[s] for s in sorted(entitled)},
            "recorded": {str(s): recorded[s] for s in sorted(recorded)},
            "cap": cap,
        }
        if self._obs is not None:
            entitled_own = sum(entitled[s] for s in owned)
            others = sum(recorded.get(s, entitled[s])
                         for s in entitled if s not in owned)
            self._obs.audit.record(
                "shard-split", "", decision=f"cap={cap}",
                rule=("global-clamp" if cap < entitled_own
                      else "share-ledger"),
                inputs={
                    "globalBudget": global_budget,
                    "ownedShards": sorted(owned),
                    "entitledOwned": entitled_own,
                    "othersRecorded": others,
                    "maxParallel": max_parallel,
                })
        return cap, max_parallel

    # ------------------------------------------------------------------
    # apply_state (upgrade_state.go:364-484)
    # ------------------------------------------------------------------
    def apply_state(self, state: ClusterUpgradeState,
                    policy: Optional[UpgradePolicySpec]) -> None:
        """One transition pass. Raises on the first HARD error; the caller
        re-reconciles (idempotence guarantees forward progress).
        TRANSIENT cluster errors (5xx/conflict/vanished object) defer
        only the affected node and the pass continues — see
        _defer_node_on_transient for why this deliberately diverges
        from the reference's abort-whole-pass semantics."""
        if state is None:
            raise ValueError("currentState should not be empty")
        self.last_pass_deferrals = 0
        with self._deferral_lock:
            self._pass_slots_freed = 0
        obs = self._obs
        if obs is not None:
            obs.audit.begin_pass()
        if policy is None or not policy.auto_upgrade:
            logger.info("auto upgrade is disabled, skipping")
            if obs is not None:
                obs.audit.record(
                    "pass", "", decision="skipped",
                    rule="auto-upgrade-disabled",
                    inputs={"policy": policy is not None})
            self._rollout = RolloutDecision()
            # no planning happens while disabled: previously reported
            # deferrals would otherwise go permanently stale
            self._clear_multislice_deferrals()
            # ...and so would gate-side drain state: a stateful eviction
            # gate (ServingDrainGate) flipped endpoints to draining when
            # it parked the node; nothing is asking for those evictions
            # any more, so hand every parked node back to the gate.
            self._abandon_stale_gate_deferrals(set())
            return

        logger.info("node states: %s", {
            str(s) or "unknown": len(state.bucket(s)) for s in ALL_STATES})

        # Declarative policy engine + artifact DAG (policy/), refreshed
        # from the policy document every pass (reference re-read
        # semantics): the engine re-points the absorbed seams (eviction
        # gate, validation verdict, canary verdict) BEFORE the guard
        # and processors below consult them; a bad document is dropped
        # whole and audited, never half-installed.
        self._policy_engine_for_pass(policy)
        dag = self._dag_for_policy(policy)
        self._refresh_validation_seam()

        # Rollout guard first: halt detection must land in the SAME pass
        # as the verdicts that tripped it — admissions below consult the
        # decision, so a halting fleet admits nothing this pass. Under
        # post-filter sharding the guard assesses the FULL snapshot:
        # the canary cohort and the halt verdicts are fleet-level
        # decisions every replica must derive identically (its durable
        # writes — the quarantine/bake stamps — are idempotent across
        # replicas). Under partition reads there IS no fleet pod join:
        # the cohort domain comes from node metadata (the shard
        # context) and cohort completion is attested per shard by each
        # shard's owner through durable DS stamps.
        full_state = (self._last_full_state
                      if self._shard_view is not None
                      and self._last_full_state is not None else state)
        shard_context = None
        if (self._partition_reads and self._shard_view is not None
                and policy.canary is not None and policy.canary.enable):
            shard_context = self._sharded_canary_context(state, policy)
        self._rollout = self.rollout_guard.assess(
            full_state, policy, self.pod_manager,
            shard_context=shard_context)
        if obs is not None and (self._rollout.halted
                                or self._rollout.canary_active):
            obs.audit.record(
                "canary", "",
                decision="halt" if self._rollout.halted
                else "canary-wave",
                rule="quarantined-revision" if self._rollout.halted
                else "canary-cohort",
                inputs={
                    "quarantined": sorted(self._rollout.quarantined),
                    "cohort": len(self._rollout.cohort or ()),
                })
        if self._rollout.quarantined:
            self._admit_rollback_nodes(state, policy)

        total_nodes = self.get_total_managed_nodes(state)
        max_parallel = policy.max_parallel_upgrades
        # Traffic-aware capacity budget (upgrade/capacity.py): with a
        # capacity-enabled policy AND a wired serving signal, the
        # effective budget — recomputed from live endpoint load every
        # pass — replaces the static count (troughs may exceed it via
        # maxEffectiveBudget, peaks shrink or pause it). Without a
        # signal the controller returns the static budget unchanged.
        capacity = self._capacity_for_policy(policy)
        static_unavailable: Optional[int] = None
        if self._shard_view is None or self.last_shard_status is None:
            # single-owner semantics (also the fallback for a snapshot
            # built before with_sharding was installed: no census means
            # no share ledger to spend against)
            max_unavailable = total_nodes
            if policy.max_unavailable is not None:
                max_unavailable = scaled_value_from_int_or_percent(
                    policy.max_unavailable, total_nodes, round_up=True)
            static_unavailable = max_unavailable
            if capacity is not None:
                max_unavailable = capacity.effective_budget(
                    max_unavailable)
        else:
            # the partition's cap comes from the durable budget-share
            # ledger, never from scaling the policy against the
            # partition (per-shard percent ceilings would jointly
            # overdraw the fleet budget); the capacity controller
            # modulates the GLOBAL budget before the split, so shards
            # jointly respect the traffic picture too
            max_unavailable, max_parallel = self._sharded_budget_caps(
                policy, capacity)
        # Safe mid-flight abort: capacity collapse (spike / node kills
        # shrinking the effective budget below what is already
        # unavailable) or a maintenance-window close overtaking a
        # mid-drain node moves drain-phase nodes to abort-required in
        # the SAME pass the condition is detected.
        self._admit_abort_nodes(state, policy, capacity, max_unavailable)
        upgrades_available = self.get_upgrades_available(
            state, max_parallel, max_unavailable)
        frozen_by_capacity = False
        if capacity is not None and capacity.budget_falling:
            # admission hysteresis: a CONTRACTING budget (spike/kill
            # ramp in progress) admits nothing — a node admitted now
            # would be aborted a pass later as the ramp continues,
            # which is churn (cordon + gate-drain + uncordon) for zero
            # progress. Aborts above still trim the existing excess;
            # admission resumes the first pass the budget stops
            # falling.
            upgrades_available = 0
            frozen_by_capacity = True
        # Rollout preflight (upgrade/preflight.py): forecast the
        # pending rollout against the learned models BEFORE slot one
        # is spent, entirely read-only (frozen-clone tripwire). A
        # required-mode threshold breach parks the rollout — zero
        # admissions, audited under preflight-rejected — until the
        # forecast clears; advisory mode records the breach and admits.
        preflight_rejected = False
        preflight = self._preflight_for_policy(policy)
        if preflight is not None:
            self.last_preflight = preflight.forecast(
                state, policy, slots=upgrades_available,
                capacity=capacity)
            if self.last_preflight["verdict"] == "reject" \
                    and upgrades_available > 0:
                upgrades_available = 0
                preflight_rejected = True
        else:
            self.last_preflight = None
        in_progress = self.get_upgrades_in_progress(state)
        unavailable_now = self.get_current_unavailable_nodes(state)
        logger.info(
            "upgrades in progress: %d, available slots: %d, "
            "unavailable nodes: %d/%d",
            in_progress, upgrades_available,
            unavailable_now, max_unavailable)
        # in-flight window observability: how full is the budget the
        # throttle lets us spend? (the eager refill exists to keep this
        # saturated — see _eager_slot_refill)
        budget = max_unavailable
        if max_parallel > 0:
            budget = min(budget, max_parallel)
        self.last_pass_slots = {
            "inProgress": in_progress,
            "available": upgrades_available,
            "budget": budget,
            "saturation": round(in_progress / budget, 4) if budget else 0.0,
        }

        self.process_done_or_unknown_nodes(state, UpgradeState.UNKNOWN)
        self.process_done_or_unknown_nodes(state, UpgradeState.DONE)
        planner = self._planner_for_policy(policy)
        if self._rollout.halted:
            # HALTED: spend zero slots — nodes already mid-flow keep
            # converging (their pods predate the bad revision or are
            # being rolled back), but nothing new is admitted.
            upgrades_available = 0
        elif self._rollout.canary_active:
            from tpu_operator_libs.topology.planner import (
                CanaryWavePlanner,
            )
            # Joint planning with slice reconfiguration: a spare
            # reserved for a remap must reach the target revision while
            # it is still OUT of the slice, so it passes through the
            # canary gate instead of parking behind the cohort (its
            # upgrade IS part of the remediation path, and it serves no
            # traffic yet).
            reserved_spares = frozenset(
                ns.node.metadata.name
                for ns in state.bucket(UpgradeState.UPGRADE_REQUIRED)
                if self.topology_keys.reserved_for_annotation
                in ns.node.metadata.annotations)
            planner = CanaryWavePlanner(planner, self._rollout.cohort,
                                        passthrough=reserved_spares)
        # Predictive wrapper OUTERMOST (PredictiveWavePlanner ∘
        # CanaryWavePlanner ∘ SlicePlanner ∘ FlatPlanner): it reorders
        # and window-gates the candidate list, while cohort filtering
        # and every budget/slice admission decision stay with the inner
        # chain untouched.
        planner = self._wrap_predictive(policy, planner)
        # Disruption-cost ranker outermost of the semantic chain
        # (DisruptionCostRanker ∘ Predictive ∘ ...): buckets candidates
        # into serving-cost tiers, exhausts cheap tiers first, and
        # holds sole-replica interactive nodes behind the prewarm arc
        # — every budget decision still lands in the inner chain.
        planner = self._wrap_cost_ranker(policy, planner)
        # Declarative policy admission outermost of ALL semantic
        # layers (PolicyAdmissionPlanner ∘ CostRanker ∘ ...): the
        # planner.admission / window.gate programs filter the
        # candidate list first, with per-node holds audited under
        # policy-* rules (fail-closed: an erroring program holds its
        # node, never the pass).
        planner = self._wrap_policy_planner(
            policy, planner,
            fleet_env={"total": total_nodes,
                       "inProgress": in_progress,
                       "unavailable": unavailable_now,
                       "slots": upgrades_available,
                       "budget": max_unavailable})
        if obs is not None:
            # the pass's slot math, with the winning rule: the record
            # every parked node's explain chain hangs off
            if self._rollout.halted:
                rule = "rollout-halt"
            elif preflight_rejected:
                rule = "preflight-rejected"
            elif frozen_by_capacity:
                rule = "capacity-falling-freeze"
            elif upgrades_available <= 0:
                rule = ("budget-saturated" if in_progress > 0
                        else "unavailable-at-cap")
            else:
                rule = "slots-free"
            inputs = {
                "totalNodes": total_nodes,
                "inProgress": in_progress,
                "unavailable": unavailable_now,
                "effectiveBudget": max_unavailable,
                "maxParallel": max_parallel,
            }
            if static_unavailable is not None:
                inputs["staticBudget"] = static_unavailable
            if self.last_preflight is not None:
                inputs["preflightVerdict"] = \
                    self.last_preflight["verdict"]
                if self.last_preflight["breaches"]:
                    inputs["preflightBreaches"] = ",".join(
                        self.last_preflight["breaches"])
            obs.audit.record(
                "budget", "", decision=f"slots={upgrades_available}",
                rule=rule, inputs=inputs)
            # audit wrapper OUTERMOST: it sees the final candidate
            # list and the final selection, so every admission edge
            # has a matching record and every held candidate gets its
            # blocking rule
            planner = _AuditingPlanner(planner, self)
        self.process_upgrade_required_nodes(
            state, upgrades_available, planner=planner)
        self.process_abort_required_nodes(state)
        self.process_cordon_required_nodes(state)
        self.process_wait_for_jobs_required_nodes(
            state, policy.wait_for_completion)
        drain_enabled = policy.drain is not None and policy.drain.enable
        self.process_pod_deletion_required_nodes(
            state, policy.pod_deletion, drain_enabled)
        self.process_drain_nodes(state, policy.drain)
        self.process_pod_restart_nodes(state)
        self.process_upgrade_failed_nodes(state)
        self.process_rollback_required_nodes(state)
        if dag is not None and self._last_namespace is not None:
            # the artifact-DAG walk runs before the validation gate
            # consults node_complete: cordoned nodes advance their
            # remaining artifacts in dependency order inside this one
            # cycle, idle nodes with stale artifacts get the re-entry
            # trigger, and a crash-looping artifact revision is
            # quarantined + suffix-rolled-back (all audited)
            dag.advance(state, self._last_namespace,
                        self._last_runtime_labels or {})
        self.process_validation_required_nodes(state)
        self.process_uncordon_required_nodes(state)
        self._eager_slot_refill(state, policy, planner, max_unavailable,
                                max_parallel, capacity=capacity)
        # Prewarm release sweep: reservations whose incumbent finished
        # are released (both stamps, one patch) — also the crash-residue
        # sweep, since a fresh incarnation re-derives reservations from
        # node annotations alone.
        if self._prewarm is not None:
            self._prewarm.sweep(state)
        # Gate-parked nodes that left every eviction-wanting state this
        # pass (policy flipped drain off, node recovered or vanished) are
        # handed back to the gate's release hook so e.g. serving
        # endpoints it set draining resume admitting requests.
        wanting = {
            ns.node.metadata.name
            for bucket in (UpgradeState.POD_DELETION_REQUIRED,
                           UpgradeState.DRAIN_REQUIRED)
            for ns in state.bucket(bucket)}
        self._abandon_stale_gate_deferrals(wanting)
        logger.info("state manager finished processing")

    def _abandon_stale_gate_deferrals(self, wanting: "set[str]") -> None:
        # Both gatekeepers get the union of eviction-wanting names: a
        # node moving pod-deletion -> drain (fallback) must not bounce
        # its endpoints through release/re-drain in between.
        self.pod_manager.abandon_stale_gate_deferrals(wanting)
        self.drain_manager.abandon_stale_gate_deferrals(wanting)

    # ------------------------------------------------------------------
    # per-state processors
    # ------------------------------------------------------------------
    @contextlib.contextmanager
    def _defer_node_on_transient(self, node: Node, action: str):
        """Context manager isolating one node's transition from
        TRANSIENT cluster errors (5xx / write conflict / object
        vanished): the node simply stays in its current state and the
        next reconcile retries it, while the rest of the pass keeps
        processing.

        Deliberate delta from the reference, which aborts the whole
        ApplyState pass on the first error (upgrade_state.go:420-423):
        under a sustained apiserver error rate an aborted pass rarely
        reaches the later state buckets of a large fleet — measured on
        the wire smoke, a 16-node upgrade through 30% injected 500s
        effectively stalled, because reaching the Nth node's write
        required every preceding request to succeed (~0.7^N per pass).
        Per-node isolation preserves idempotence (a deferred node is
        indistinguishable from one the snapshot missed) and keeps the
        fleet converging at the per-node success rate instead of the
        per-pass one. Hard errors (anything not a transient seam
        error) still abort the pass, exactly like the reference
        (pinned by test_cordon_failure_aborts_pass)."""
        try:
            yield
        except (ApiServerError, ConflictError, NotFoundError) as exc:
            logger.warning(
                "transient cluster error during %s for node %s; "
                "deferring the node to the next reconcile: %s",
                action, node.metadata.name, exc)
            with self._deferral_lock:
                self._transient_deferrals += 1
                self.last_pass_deferrals += 1

    def _map_bucket(self, items: list, action: str,
                    body: Callable) -> list:
        """Run ``body(item)`` per item under per-node transient
        isolation — on the bounded worker pool when one is configured,
        else serially. Results come back in input order (None for
        deferred items); the pool barrier means the whole bucket has
        committed before this returns, so bucket ordering, crash-resume
        and the chaos monitor's per-tick audits all see the same
        pass structure as the serial reference. Hard errors surface
        after the barrier (serial mode: immediately), aborting the pass
        exactly like the reference."""
        def one(item):
            node = item.node if isinstance(item, NodeUpgradeState) else item
            with self._defer_node_on_transient(node, action):
                return body(item)
            return None  # transient error: node deferred to next pass

        # Small buckets run inline: fanning out 2-3 items costs more in
        # thread spawn than the overlap buys; the pool earns its keep on
        # wave-sized buckets (maxUnavailable worth of write round-trips).
        if self._pool is None or len(items) < 4:
            return [one(item) for item in items]
        return self._pool.map_wait(
            [lambda it=item: one(it) for item in items])

    def process_done_or_unknown_nodes(self, state: ClusterUpgradeState,
                                      bucket: UpgradeState) -> None:
        """Decide done vs upgrade-required for idle nodes
        (upgrade_state.go:486-550)."""
        def triage(ns: NodeUpgradeState) -> None:
            pod_synced, orphaned = self._pod_in_sync_with_ds(ns)
            upgrade_requested = self._is_upgrade_requested(ns.node)
            waiting_safe_load = (
                self.safe_load_manager.is_waiting_for_safe_load(
                    ns.node))
            if (not pod_synced and not orphaned) or waiting_safe_load \
                    or upgrade_requested:
                if self._rollout.halted:
                    # HALTED fleet: the out-of-sync target is the
                    # quarantined revision — admitting the node would
                    # feed it to the bad build. It stays idle until the
                    # rollback (or a new DS spec) lifts the halt.
                    logger.info(
                        "fleet halted; node %s stays idle instead of "
                        "entering the upgrade flow", ns.node.metadata.name)
                    return
                if self._skip_node_upgrade(ns.node):
                    # Honor the skip label HERE, not only at
                    # admission: a remediation-parked node is
                    # typically CORDONED by that machine, and
                    # entering upgrade-required now would capture
                    # that quarantine cordon as the "node was
                    # unschedulable before the upgrade" memory —
                    # the upgrade would then finish without an
                    # uncordon and strand the node (found by the
                    # chaos harness, seed 10).
                    logger.info(
                        "node %s is marked to skip upgrades; "
                        "leaving idle", ns.node.metadata.name)
                    return
                annotations: dict[str, Optional[str]] = {}
                if ns.node.is_unschedulable():
                    # Remember pre-upgrade cordon so we restore it at
                    # the end (upgrade_state.go:509-523).
                    annotations[self.keys.initial_state_annotation] = \
                        TRUE_STRING
                elif self.keys.initial_state_annotation \
                        in ns.node.metadata.annotations:
                    # Crash residue: the finishing pass committed the
                    # state but died before deleting the marker. A
                    # SCHEDULABLE node starting a new upgrade with it
                    # would be remembered as "cordoned before the
                    # upgrade" and left cordoned forever at its end.
                    annotations[self.keys.initial_state_annotation] = None
                # annotation bookkeeping rides the state transition's
                # merge patch: one write, crash-atomic
                self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.UPGRADE_REQUIRED,
                    annotations=annotations or None)
                logger.info("node %s requires upgrade",
                            ns.node.metadata.name)
                return
            if bucket == UpgradeState.DONE and \
                    self.keys.initial_state_annotation \
                    in ns.node.metadata.annotations:
                # Crash residue on an idle node (the finish path
                # deletes the marker right after the DONE commit);
                # the cordon itself is untouched — DONE+marker only
                # arises on the pre-cordoned arc, which must stay
                # cordoned.
                self.provider.change_node_upgrade_annotation(
                    ns.node, self.keys.initial_state_annotation, None)
            if bucket == UpgradeState.UNKNOWN:
                self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.DONE)

        self._map_bucket(state.bucket(bucket), "idle triage", triage)

    @property
    def multislice_deferred_slices(self) -> tuple[str, ...]:
        """Slices the most recent slice-planning round deferred because
        their DCN job's member-slice budget was exhausted (empty when no
        constraint is active or nothing was deferred)."""
        if self._multislice_constraint is None:
            return ()
        return self._multislice_constraint.last_deferred

    def with_multislice_constraint(
            self, constraint: "MultisliceConstraint",
    ) -> "ClusterUpgradeStateManager":
        """Install a custom multislice constraint (own job-label keys /
        workload-pod source / budget) used when ``topology_mode=slice``.
        A custom constraint is authoritative: the policy's
        ``maxUnavailableSlicesPerJob`` does not override its budget."""
        self._multislice_constraint = constraint
        self._multislice_constraint_is_custom = True
        return self

    def _planner_for_policy(
            self, policy: UpgradePolicySpec) -> UpgradePlanner:
        if self._explicit_planner is None and policy.topology_mode == "slice":
            from tpu_operator_libs.topology.planner import SlicePlanner
            return SlicePlanner(self._multislice_for_policy(policy),
                                topology_keys=self.topology_keys)
        # The slice planner is not running, so nothing enforces (or
        # refreshes) multislice deferrals — stale ones must not keep
        # reporting through status/metrics after a switch to flat mode
        # or an explicit planner.
        self._clear_multislice_deferrals()
        return self._explicit_planner or FlatPlanner()

    def _clear_multislice_deferrals(self) -> None:
        if self._multislice_constraint is not None:
            self._multislice_constraint.last_deferred = ()

    # ------------------------------------------------------------------
    # traffic-aware capacity budgets (upgrade/capacity.py)
    # ------------------------------------------------------------------
    def with_serving_signal(
            self, source: "object") -> "ClusterUpgradeStateManager":
        """Install (or clear with None) the serving-endpoint source —
        a callable returning ``{node_name: [ServingEndpoint, ...]}`` —
        the :class:`~tpu_operator_libs.upgrade.capacity.
        CapacityBudgetController` aggregates into fleet headroom. The
        controller itself is created from the policy
        (``capacityBudget.enable``); with the spec enabled but no
        source wired it fails open to the static budget exactly."""
        self._capacity_source = source
        if self._capacity is not None:
            self._capacity.set_source(source)
        return self

    @property
    def capacity_controller(self) -> "object":
        """The persistent CapacityBudgetController (None until a
        capacity-enabled policy ran)."""
        return self._capacity

    def with_prewarm_hooks(
            self, readiness: "object",
            release: "object" = None) -> "ClusterUpgradeStateManager":
        """Install the deployment's prewarm seams (upgrade/handover.py):
        ``readiness(spare, incumbent, model, cls) -> bool`` brings the
        replacement replica up (first call) and reports when it passes
        readiness; ``release(spare, incumbent)`` (optional) lets the
        serving side retire the replica once the incumbent finished."""
        self._prewarm_readiness = readiness
        self._prewarm_release = release
        if self._prewarm is not None:
            self._prewarm.readiness = readiness
            self._prewarm.release = release
        return self

    @property
    def cost_ranker(self) -> "object":
        """The persistent DisruptionCostRanker (None until a policy
        with trafficClasses ran with a wired serving signal)."""
        return self._cost_ranker

    @property
    def prewarm_coordinator(self) -> "object":
        """The persistent PrewarmCoordinator (None until a prewarm-
        enabled policy ran)."""
        return self._prewarm

    def _wrap_cost_ranker(self, policy: UpgradePolicySpec,
                          inner: UpgradePlanner) -> UpgradePlanner:
        """Wrap ``inner`` in the DisruptionCostRanker when the policy
        declares traffic classes AND a serving signal is wired;
        otherwise clear any stale holds and return ``inner`` unchanged
        (class-blind fleets keep PR 10 semantics bit for bit)."""
        spec = policy.capacity
        active = (spec is not None and spec.enable
                  and bool(spec.traffic_classes)
                  and self._capacity_source is not None)
        if not active:
            if self._cost_ranker is not None:
                self._cost_ranker.last_holds = {}
                self._cost_ranker.last_rank = None
            return inner
        from tpu_operator_libs.consts import RemediationKeys
        from tpu_operator_libs.upgrade.handover import (
            DisruptionCostRanker,
            PrewarmCoordinator,
        )

        # the precursor's at-risk stamp (remediation namespace, same
        # driver/domain as this manager's keys): a condemned-at-risk
        # candidate is already being routed around, so disrupting it
        # first is free — the ranker pins it to the idle tier
        at_risk_key = RemediationKeys(
            driver=self.keys.driver,
            domain=self.keys.domain).at_risk_annotation
        if spec.prewarm and self._prewarm is None:
            self._prewarm = PrewarmCoordinator(
                self.provider, self.keys, clock=self.clock,
                readiness=self._prewarm_readiness,
                release=self._prewarm_release,
                audit=self._prewarm_audit_hook)
        if self._cost_ranker is None:
            self._cost_ranker = DisruptionCostRanker(
                inner, self._capacity_source, spec.class_map(),
                prewarm=self._prewarm if spec.prewarm else None,
                audit=self._ranker_audit_hook,
                at_risk_annotation=at_risk_key)
        ranker = self._cost_ranker
        ranker.inner = inner
        ranker._source = self._capacity_source
        ranker.classes = spec.class_map()
        ranker.prewarm = self._prewarm if spec.prewarm else None
        ranker.at_risk_annotation = at_risk_key
        return ranker

    def _ranker_audit_hook(self, kind: str, node: str, decision: str,
                           rule: str, inputs: dict) -> None:
        """Rich hold record (model/class/prewarm arc) — the audit
        wrapper's later generic hold for the same node dedups against
        it on the shared rule."""
        if self._obs is not None:
            self._obs.audit.record_hold(node, rule, inputs=inputs)

    def _prewarm_audit_hook(self, kind: str, node: str, decision: str,
                            rule: str, inputs: dict) -> None:
        if self._obs is not None:
            self._obs.audit.record(kind, node, decision=decision,
                                   rule=rule, inputs=inputs)

    # ------------------------------------------------------------------
    # declarative policy engine + artifact DAG (policy/)
    # ------------------------------------------------------------------
    @property
    def policy_engine(self) -> "object":
        """The persistent PolicyEngine (None until a policy carrying
        policyHooks ran)."""
        return self._policy_engine

    @property
    def dag_coordinator(self) -> "object":
        """The persistent ArtifactDAGCoordinator (None until a policy
        carrying artifactDAG ran)."""
        return self._dag

    def _policy_audit_hook(self, kind: str, subject: str,
                           decision: str, rule: str,
                           inputs: dict) -> None:
        """DecisionAudit bridge for the engine/coordinator (reads
        ``self._obs`` at call time, so installing observability later
        lights the records up without rewiring)."""
        if self._obs is not None:
            self._obs.audit.record(kind, subject, decision=decision,
                                   rule=rule, inputs=inputs)

    def _policy_engine_for_pass(self, policy: UpgradePolicySpec) -> "object":
        """Create/refresh the engine from the pass's policy and
        re-point the absorbed seams. Returns the engine when any hook
        is active, else None."""
        spec = getattr(policy, "policy_hooks", None)
        active = (spec is not None and getattr(spec, "enable", False)
                  and bool(getattr(spec, "hooks", ())))
        if self._policy_engine is None:
            if not active:
                return None
            from tpu_operator_libs.policy.engine import PolicyEngine

            self._policy_engine = PolicyEngine(
                self.keys, audit=self._policy_audit_hook)
        engine = self._policy_engine
        engine.refresh(spec if active else None)
        engine.begin_pass()
        self._install_policy_gate(engine)
        self.rollout_guard.extra_verdict = (
            engine.canary_verdict
            if engine.registry.has("canary.verdict") else None)
        return engine if engine.active else None

    def _install_policy_gate(self, engine: "object") -> None:
        """Wrap (or unwrap) the installed EvictionGate with the ONE
        persistent policy gate. Identity-stable across passes, so the
        GateKeepers never release/re-park on a steady reconcile."""
        current = self.pod_manager.eviction_gate
        if engine.registry.has("eviction.filter"):
            if self._policy_gate is None:
                from tpu_operator_libs.policy.engine import (
                    PolicyEvictionGate,
                )

                self._policy_gate = PolicyEvictionGate()
            gate = self._policy_gate
            gate.engine = engine
            if current is not gate:
                gate.inner = current
                self.with_eviction_gate(gate)
        elif self._policy_gate is not None \
                and current is self._policy_gate:
            self.with_eviction_gate(self._policy_gate.inner)

    def _dag_for_policy(self, policy: UpgradePolicySpec) -> "object":
        """Create/refresh the artifact-DAG coordinator from the
        pass's policy; None when the spec is absent/disabled."""
        spec = getattr(policy, "artifact_dag", None)
        active = (spec is not None and getattr(spec, "enable", False)
                  and bool(getattr(spec, "artifacts", ())))
        if not active:
            if self._dag is not None:
                self._dag.spec = None  # deactivates node_complete too
            return None
        if self._dag is None:
            from tpu_operator_libs.policy.dag import (
                ArtifactDAGCoordinator,
            )

            self._dag = ArtifactDAGCoordinator(
                self.client, self.keys, self.provider,
                clock=self.clock, audit=self._policy_audit_hook,
                pod_failure_threshold=POD_RESTART_FAILURE_THRESHOLD)
        self._dag.refresh(spec)
        return self._dag

    def _refresh_validation_seam(self) -> None:
        """Compose the ValidationManager's policy seam from the active
        parts: the validation.verdict program (fail-closed park on
        program failure) and the DAG completion gate (park while
        artifacts advance)."""
        engine = self._policy_engine
        dag = self._dag
        parts = []
        if engine is not None \
                and engine.registry.has("validation.verdict"):
            def program_gate(node, _engine=engine):
                return _engine.validation_gate(node, self.clock.now())

            parts.append(program_gate)
        if dag is not None and dag.active:
            def dag_gate(node, _dag=dag):
                return None if _dag.node_complete(node) \
                    else "policy-park"

            parts.append(dag_gate)
        if not parts:
            self.validation_manager.policy_validator = None
            return

        def composed(node, _parts=tuple(parts)):
            for part in _parts:
                verdict = part(node)
                if verdict:
                    return verdict
            return None

        self.validation_manager.policy_validator = composed

    def _wrap_policy_planner(self, policy: UpgradePolicySpec,
                             inner: UpgradePlanner,
                             fleet_env: dict) -> UpgradePlanner:
        """Wrap ``inner`` in the PolicyAdmissionPlanner when any
        admission program is registered; otherwise return it
        unchanged (policy-free fleets keep prior semantics bit for
        bit)."""
        engine = self._policy_engine
        if engine is None or not (
                engine.registry.has("planner.admission")
                or engine.registry.has("window.gate")):
            return inner
        from tpu_operator_libs.policy.engine import (
            PolicyAdmissionPlanner,
        )

        if self._policy_planner is None:
            self._policy_planner = PolicyAdmissionPlanner(inner, engine)
        wrapper = self._policy_planner
        wrapper.inner = inner
        wrapper.engine = engine
        wrapper.fleet_env = fleet_env
        wrapper.now = self.clock.now()
        window = policy.maintenance_window
        wrapper.window_close = (
            window.close_at(wrapper.now)
            if window is not None and window.enable else None)
        return wrapper

    def _capacity_for_policy(self, policy: UpgradePolicySpec) -> "object":
        """The controller for this pass, created/refreshed from the
        policy (re-read every pass, reference semantics); None when the
        spec is absent or disabled."""
        spec = policy.capacity
        if spec is None or not spec.enable:
            return None
        if self._capacity is None:
            from tpu_operator_libs.upgrade.capacity import (
                CapacityBudgetController,
            )

            self._capacity = CapacityBudgetController(
                spec, source=self._capacity_source, clock=self.clock,
                nudger=self.nudger)
        else:
            self._capacity.spec = spec
            self._capacity.nudger = self.nudger
        return self._capacity

    def _preflight_for_policy(self, policy: UpgradePolicySpec) -> "object":
        """The preflight forecaster for this pass (same lifecycle as
        :meth:`_capacity_for_policy`: created on first use, knobs and
        collaborators re-pointed every pass from the re-read policy);
        None when the spec is absent or ``mode`` is ``off``."""
        spec = policy.preflight
        if spec is None or not spec.enabled:
            return None
        if self._preflight is None:
            from tpu_operator_libs.upgrade.preflight import (
                PreflightForecaster,
            )

            self._preflight = PreflightForecaster(
                spec, self.keys,
                predictor=self._predictor_for_policy(policy),
                clock=self.clock,
                trace=self.preflight_trace,
                guard=self.preflight_guard,
                live_call_counts=getattr(
                    self.client, "api_call_counts", None))
        else:
            self._preflight.refresh(spec)
            self._preflight.predictor = \
                self._predictor_for_policy(policy)
            self._preflight.trace = self.preflight_trace
            self._preflight.guard = self.preflight_guard
        return self._preflight

    @property
    def preflight(self) -> "object":
        """The persistent PreflightForecaster (None until a preflight
        policy ran) — its ``last_forecast`` is the what-if picture."""
        return self._preflight

    @property
    def predictor(self) -> "object":
        """The persistent :class:`~tpu_operator_libs.upgrade.predictor.
        PhaseDurationPredictor` (None until a predictive policy ran)."""
        return self._predictor

    @property
    def predictive_planner(self) -> "object":
        """The persistent PredictiveWavePlanner wrapper (None until a
        predictive policy ran) — its ``last_plan`` is the fleet ETA."""
        return self._predictive_planner

    def _wrap_predictive(self, policy: UpgradePolicySpec,
                         inner: UpgradePlanner) -> UpgradePlanner:
        """Wrap ``inner`` in the predictive LPT/window planner when the
        policy asks for it; otherwise detach the learning observer and
        return ``inner`` unchanged (reference semantics, bit for bit —
        with no observer installed not a single extra annotation is
        written)."""
        spec = policy.predictor
        if spec is None or not spec.enable:
            # no predictor: the tracer (when installed) stays the sole
            # observer; with neither, not a single annotation is written
            self._install_transition_observer(predictor_active=False)
            if policy.maintenance_window is not None \
                    and policy.maintenance_window.enable:
                logger.warning(
                    "maintenanceWindow is set but the predictor is "
                    "disabled: the window gate needs duration "
                    "estimates; ignoring the window")
            return inner
        from tpu_operator_libs.upgrade.predictor import (
            PredictiveWavePlanner,
        )

        self._predictor_for_policy(policy)
        self._install_transition_observer(predictor_active=True)
        if self._predictive_planner is None:
            self._predictive_planner = PredictiveWavePlanner(
                inner, self._predictor, clock=self.clock)
        wrapper = self._predictive_planner
        wrapper.inner = inner
        wrapper.window = policy.maintenance_window
        wrapper.audit = self._window_audit_hooks()
        return wrapper

    def _window_audit_hooks(self):
        """The window admit/defer hook handed to the predictive
        planner: the externally-installed ``window_audit`` (the chaos
        monitor's invariant feed) fanned out with the decision audit's
        recorder, either alone when only one is present."""
        hooks = [hook for hook in (self.window_audit,
                                   self._obs_window_hook
                                   if self._obs is not None else None)
                 if hook is not None]
        if not hooks:
            return None
        if len(hooks) == 1:
            return hooks[0]

        def fan_out(kind, node, at, predicted_done, _hooks=tuple(hooks)):
            for hook in _hooks:
                hook(kind, node, at, predicted_done)

        return fan_out

    def _obs_window_hook(self, kind: str, node: str, at: float,
                         predicted_done: float) -> None:
        self._obs.audit.record(
            "window", node, decision=kind, rule="maintenance-window",
            inputs={"predictedDone": round(predicted_done, 1),
                    "at": round(at, 1)})

    def _predictor_for_policy(self, policy: UpgradePolicySpec) -> "object":
        """The duration predictor for this pass, created/refreshed from
        the policy (None when prediction is disabled). Split out of
        :meth:`_wrap_predictive` because the mid-flight abort admission
        needs remaining-duration estimates BEFORE the planner wrapping
        runs — including on a fresh incarnation's very first pass after
        a crash, where mid-flight nodes already exist."""
        spec = policy.predictor
        if spec is None or not spec.enable:
            return None
        if self._predictor is None:
            from tpu_operator_libs.upgrade.predictor import (
                PhaseDurationPredictor,
            )

            self._predictor = PhaseDurationPredictor(
                self.keys, clock=self.clock, smoothing=spec.smoothing,
                prior_seconds=spec.prior_seconds)
        else:
            # the policy is re-read every pass (reference semantics):
            # knob changes take effect without dropping learned state
            self._predictor.smoothing = spec.smoothing
            self._predictor.prior_seconds = spec.prior_seconds
        return self._predictor

    def _multislice_for_policy(
            self, policy: UpgradePolicySpec) -> "MultisliceConstraint":
        """The persistent multislice constraint for slice-mode planning.

        Auto-created on first use over a job-label-selector pod list
        (all namespaces — JobSet workloads live outside the runtime
        namespace); the policy is re-read every pass (reference
        semantics, upgrade_state.go:364-365), so a changed
        ``maxUnavailableSlicesPerJob`` takes effect immediately unless a
        custom constraint was installed via
        :meth:`with_multislice_constraint`.
        """
        from tpu_operator_libs.topology.multislice import (
            MultisliceConstraint,
            default_workload_pods,
        )
        if self._multislice_constraint is None:
            self._multislice_constraint = MultisliceConstraint(
                workload_pods=default_workload_pods(self.client),
                max_unavailable_slices_per_job=(
                    policy.max_unavailable_slices_per_job))
        elif not self._multislice_constraint_is_custom:
            self._multislice_constraint.max_down = (
                policy.max_unavailable_slices_per_job)
        return self._multislice_constraint

    def process_upgrade_required_nodes(
            self, state: ClusterUpgradeState, upgrades_available: int,
            planner: Optional[UpgradePlanner] = None) -> None:
        """Start upgrades for as many nodes as the throttle allows
        (upgrade_state.go:587-631), selection delegated to the planner.

        ``apply_state`` resolves the planner from the policy's
        topology_mode; direct callers get the explicit planner (or flat)
        unless they pass one.
        """
        planner = planner or self.planner

        def triage(ns: NodeUpgradeState) -> Optional[NodeUpgradeState]:
            if self._is_upgrade_requested(ns.node):
                # one-shot trigger: consume the annotation
                self.provider.change_node_upgrade_annotation(
                    ns.node, self.keys.upgrade_requested_annotation,
                    None)
            if self._skip_node_upgrade(ns.node):
                logger.info("node %s is marked to skip upgrades",
                            ns.node.metadata.name)
                return None
            return ns

        # triage fans out; ADMISSION does not: planner.plan runs once,
        # serially, over the ordered candidate list — the single point
        # where the max-unavailable / max-parallel budgets are spent,
        # which is what keeps the chaos invariants exact under the
        # parallel pool.
        candidates = [ns for ns in self._map_bucket(
            state.bucket(UpgradeState.UPGRADE_REQUIRED),
            "upgrade triage", triage) if ns is not None]

        def start(ns: NodeUpgradeState) -> None:
            # a deferred node's slot stays consumed for this pass —
            # conservative under the throttle, corrected next pass
            self.provider.change_node_upgrade_state(
                ns.node, UpgradeState.CORDON_REQUIRED)
            logger.info("node %s waiting for cordon",
                        ns.node.metadata.name)

        # Under sharding the planner sees the FULL snapshot (candidates
        # stay partition-local): slice grouping and multislice-job
        # budgets are fleet-wide truths, and a partition-local view
        # would let two replicas jointly overdraw a DCN job's member
        # budget or split a slice wave.
        plan_state = state
        if self._shard_view is not None and self._last_full_state \
                is not None:
            plan_state = self._last_full_state
        self._map_bucket(
            planner.plan(candidates, upgrades_available, plan_state),
            "upgrade start", start)

    def process_cordon_required_nodes(self, state: ClusterUpgradeState) -> None:
        """Cordon and advance to wait-for-jobs (upgrade_state.go:635-654)."""
        def cordon(ns: NodeUpgradeState) -> None:
            self.cordon_manager.cordon(ns.node)
            self.provider.change_node_upgrade_state(
                ns.node, UpgradeState.WAIT_FOR_JOBS_REQUIRED)

        self._map_bucket(state.bucket(UpgradeState.CORDON_REQUIRED),
                         "cordon", cordon)

    def process_wait_for_jobs_required_nodes(
            self, state: ClusterUpgradeState,
            wait_spec: Optional[WaitForCompletionSpec]) -> None:
        """Wait for workload completion or skip straight on when no
        selector is configured (upgrade_state.go:658-693)."""
        nodes = [ns.node for ns in
                 state.bucket(UpgradeState.WAIT_FOR_JOBS_REQUIRED)]
        if wait_spec is None or not wait_spec.pod_selector:
            next_state = (UpgradeState.POD_DELETION_REQUIRED
                          if self._pod_deletion_enabled
                          else UpgradeState.DRAIN_REQUIRED)

            def advance(node: Node) -> None:
                try:
                    self.provider.change_node_upgrade_state(node, next_state)
                except Exception as exc:  # noqa: BLE001 — reference ignores
                    # this error (upgrade_state.go:673)
                    logger.error("failed to advance node %s: %s",
                                 node.metadata.name, exc)

            self._map_bucket(nodes, "wait-for-jobs skip", advance)
            return
        if not nodes:
            return
        self.pod_manager.schedule_check_on_pod_completion(PodManagerConfig(
            nodes=nodes, wait_for_completion_spec=wait_spec))

    def process_pod_deletion_required_nodes(
            self, state: ClusterUpgradeState,
            deletion_spec: Optional[PodDeletionSpec],
            drain_enabled: bool) -> None:
        """Evict filter-selected workload pods (upgrade_state.go:698-727)."""
        nodes = [ns.node for ns in
                 state.bucket(UpgradeState.POD_DELETION_REQUIRED)]
        if not self._pod_deletion_enabled:
            def advance(node: Node) -> None:
                try:
                    self.provider.change_node_upgrade_state(
                        node, UpgradeState.DRAIN_REQUIRED)
                except Exception as exc:  # noqa: BLE001 — reference ignores
                    # this error (upgrade_state.go:706)
                    logger.error("failed to advance node %s: %s",
                                 node.metadata.name, exc)

            self._map_bucket(nodes, "pod-deletion-disabled skip", advance)
            return
        if not nodes:
            return
        self.pod_manager.schedule_pod_eviction(PodManagerConfig(
            nodes=nodes, deletion_spec=deletion_spec,
            drain_enabled=drain_enabled))

    def process_drain_nodes(self, state: ClusterUpgradeState,
                            drain_spec: Optional[DrainSpec]) -> None:
        """Schedule async drains, or skip the stage when disabled
        (upgrade_state.go:731-760)."""
        nodes = [ns.node for ns in state.bucket(UpgradeState.DRAIN_REQUIRED)]
        if drain_spec is None or not drain_spec.enable:
            self._map_bucket(
                nodes, "drain-disabled skip",
                lambda node: self.provider.change_node_upgrade_state(
                    node, UpgradeState.POD_RESTART_REQUIRED))
            return
        if not nodes:
            return
        self.drain_manager.schedule_nodes_drain(
            DrainConfiguration(spec=drain_spec, nodes=nodes))

    def process_pod_restart_nodes(self, state: ClusterUpgradeState) -> None:
        """Restart outdated runtime pods; advance nodes whose new pod is
        ready (upgrade_state.go:764-831)."""
        def triage(ns: NodeUpgradeState) -> Optional[Pod]:
            pod_synced, orphaned = self._pod_in_sync_with_ds(ns)
            if not pod_synced or orphaned:
                if (not orphaned and self._rollout.quarantined_active
                        and self.pod_manager.get_daemon_set_revision_hash(
                            ns.runtime_daemon_set)
                        in self._rollout.quarantined_active):
                    # the DS still points at a quarantined revision
                    # (rollback pending or disabled): restarting now
                    # would mint another pod of the bad build
                    logger.info(
                        "holding pod restart on node %s: target revision "
                        "is quarantined", ns.node.metadata.name)
                    return None
                # Only restart pods not already terminating
                # (upgrade_state.go:775-781).
                if ns.runtime_pod.metadata.deletion_timestamp is None:
                    return ns.runtime_pod
                return None
            # Pod template is current: release any blocked safe load,
            # then wait for readiness.
            self.safe_load_manager.unblock_loading(ns.node)
            if self._is_runtime_pod_in_sync(ns):
                if not self._validation_enabled \
                        and not self._policy_validation_active:
                    self._update_node_to_uncordon_or_done(ns.node)
                    return None
                self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.VALIDATION_REQUIRED)
            elif ns.runtime_pod.is_failing(
                    POD_RESTART_FAILURE_THRESHOLD):
                logger.info("runtime pod failing on node %s with "
                            "repeated restarts", ns.node.metadata.name)
                self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.FAILED)
            return None

        pods_to_restart = [
            pod for pod in self._map_bucket(
                state.bucket(UpgradeState.POD_RESTART_REQUIRED),
                "pod restart", triage)
            if pod is not None]
        if self._pool is not None and len(pods_to_restart) >= 4:
            # Restart deletes are independent per pod: pipeline the
            # write wave on the pool instead of one blocking round-trip
            # at a time. Per-pod batches keep schedule_pods_restart's
            # transient-vs-hard error semantics intact.
            deferred_pods = sum(self._pool.map_wait(
                [lambda p=pod: self.pod_manager.schedule_pods_restart([p])
                 for pod in pods_to_restart]))
        else:
            deferred_pods = self.pod_manager.schedule_pods_restart(
                pods_to_restart)
        with self._deferral_lock:
            self._transient_deferrals += deferred_pods
            self.last_pass_deferrals += deferred_pods

    def process_upgrade_failed_nodes(self, state: ClusterUpgradeState) -> None:
        """Auto-recover failed nodes whose pod became healthy
        (upgrade_state.go:835-877).

        Deliberate delta from the reference: when validation is enabled,
        recovery also requires the validation gate to pass. The reference
        recovers on pod-readiness alone, which lets a node that *failed
        validation* (e.g. validation timeout with a degraded ICI fabric)
        slip back into service the moment its runtime pod is Ready —
        bypassing the very gate that failed it. Pod-level failures recover
        exactly as before; gate-level failures stay failed until the gate
        passes.
        """
        def recover(ns: NodeUpgradeState) -> None:
            if self._skip_node_upgrade(ns.node):
                # The remediation machine parks a node it quarantines
                # behind the skip label (cordon → recovery). A FAILED
                # node under that quarantine must wait it out: acting
                # here — uncordon-on-healthy or the drain re-entry —
                # would have two machines driving one node mid-ladder.
                # (A user-set skip reads the same way: hands off.)
                logger.info(
                    "failed node %s carries the skip label (remediation "
                    "quarantine or operator opt-out); holding recovery",
                    ns.node.metadata.name)
                return
            synced, orphaned = self._pod_in_sync_with_ds(ns)
            if not synced and not orphaned \
                    and ns.runtime_pod.is_ready():
                # The DaemonSet rolled a NEW revision while the node
                # sat failed (its crash-loop healed on the old one,
                # or a drain failed): a healthy-but-outdated pod can
                # never become "in sync" on its own, so the
                # pod-healthy recovery below would wait forever —
                # the node is stranded (found by the chaos harness,
                # seed 113). Resume via drain-required: the drain
                # retries (covering the drain-failure origin without
                # ever skipping workload eviction) and the flow then
                # restarts the pod onto the current revision.
                logger.info(
                    "failed node %s has a healthy but outdated pod; "
                    "re-entering the upgrade flow at drain",
                    ns.node.metadata.name)
                self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.DRAIN_REQUIRED)
                return
            if not self._is_runtime_pod_in_sync(ns):
                return
            # check(), not validate(): the recovery gate must not
            # stamp or expire validation timers on an already-failed
            # node.
            if (self._validation_enabled
                    or self._policy_validation_active) \
                    and not self.validation_manager.check(ns.node):
                logger.info("failed node %s has a healthy pod but has "
                            "not passed validation; holding",
                            ns.node.metadata.name)
                return
            self._update_node_to_uncordon_or_done(ns.node)

        self._map_bucket(state.bucket(UpgradeState.FAILED),
                         "failed-node recovery", recover)

    # ------------------------------------------------------------------
    # canary rollback (beyond-reference; see upgrade/rollout_guard.py)
    # ------------------------------------------------------------------
    def _admit_rollback_nodes(self, state: ClusterUpgradeState,
                              policy: UpgradePolicySpec) -> None:
        """Move nodes stuck on a QUARANTINED revision out of
        failed/validation-required into rollback-required — the fleet
        decided their revision is bad, so waiting for the pod to heal
        (it never will) or validating it (it already lost) is pointless.
        Runs right after the guard's assessment so the transition lands
        in the same pass as the halt; the snapshot buckets are updated
        in place so later processors never act on a stale membership."""
        if policy.rollback is not None and not policy.rollback.enable:
            return
        bad = self._rollout.quarantined
        for source in (UpgradeState.FAILED,
                       UpgradeState.VALIDATION_REQUIRED):
            bucket = state.node_states.get(str(source), [])
            moved: list[NodeUpgradeState] = []
            for ns in bucket:
                if ns.is_orphaned():
                    continue
                try:
                    pod_hash = self.pod_manager.get_pod_revision_hash(
                        ns.runtime_pod)
                except RevisionHashError:
                    continue
                if pod_hash not in bad:
                    continue
                with self._defer_node_on_transient(ns.node,
                                                   "rollback admit"):
                    if self.provider.change_node_upgrade_state(
                            ns.node, UpgradeState.ROLLBACK_REQUIRED):
                        logger.info(
                            "node %s is on quarantined revision %s; "
                            "rolling back", ns.node.metadata.name,
                            pod_hash)
                        moved.append(ns)
            for ns in moved:
                bucket.remove(ns)
                state.node_states.setdefault(
                    str(UpgradeState.ROLLBACK_REQUIRED), []).append(ns)

    def process_rollback_required_nodes(
            self, state: ClusterUpgradeState) -> None:
        """Drive rolled-back nodes home: restart the condemned pod onto
        the re-pinned previous revision, then revalidate and return the
        node to service. The node stayed cordoned through its whole
        failed upgrade, so no fresh drain is needed — its workloads were
        already evicted on the way in."""
        def triage(ns: NodeUpgradeState) -> Optional[Pod]:
            if ns.is_orphaned():
                return None  # no DS, nothing to re-pin against
            ds_hash = self.pod_manager.get_daemon_set_revision_hash(
                ns.runtime_daemon_set)
            quarantined = ns.runtime_daemon_set.metadata.annotations.get(
                self.keys.quarantined_revision_annotation)
            pod_hash = self.pod_manager.get_pod_revision_hash(
                ns.runtime_pod)
            if pod_hash == quarantined:
                if ds_hash == quarantined:
                    # rollback has not re-pinned the DS yet (guard retry
                    # in flight, or rollback disabled): deleting now
                    # would just recreate the bad build
                    return None
                if ns.runtime_pod.metadata.deletion_timestamp is None:
                    return ns.runtime_pod
                return None
            # pod is off the condemned hash: wait for sync+ready, then
            # hand back through the standard validation/uncordon arc
            if self._is_runtime_pod_in_sync(ns):
                if not self._validation_enabled \
                        and not self._policy_validation_active:
                    self._update_node_to_uncordon_or_done(ns.node)
                    return None
                self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.VALIDATION_REQUIRED)
            elif ns.runtime_pod.is_failing(POD_RESTART_FAILURE_THRESHOLD):
                logger.info("rollback pod failing on node %s with "
                            "repeated restarts", ns.node.metadata.name)
                self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.FAILED)
            return None

        pods_to_restart = [
            pod for pod in self._map_bucket(
                state.bucket(UpgradeState.ROLLBACK_REQUIRED),
                "rollback restart", triage)
            if pod is not None]
        deferred_pods = self.pod_manager.schedule_pods_restart(
            pods_to_restart)
        with self._deferral_lock:
            self._transient_deferrals += deferred_pods
            self.last_pass_deferrals += deferred_pods

    # ------------------------------------------------------------------
    # safe mid-flight abort (beyond-reference; docs/traffic-aware-
    # budgets.md)
    # ------------------------------------------------------------------
    def _admit_abort_nodes(self, state: ClusterUpgradeState,
                           policy: UpgradePolicySpec,
                           capacity: "object",
                           effective_budget: int) -> None:
        """Move drain-phase nodes to ``abort-required`` when the fleet
        can no longer afford their disruption.

        Two triggers, checked per node over the ABORTABLE (pre-restart)
        buckets in least-progressed-first order:

        - **capacity collapse**: current unavailability exceeds the
          effective budget (a traffic spike shrank it, or concurrent
          node kills consumed it) — abort exactly the excess, cheapest
          nodes first;
        - **maintenance-window close**: the window has closed, or the
          node's predicted remaining duration (durable phase stamps +
          learned model) now overruns it — the PR 9 admission gate only
          protected the START; this bounds prediction-error stragglers
          mid-flight.

        Snapshot buckets are updated in place (the rollback-admission
        idiom) so later processors never act on stale membership, and
        the transition is a single durable label write — crash-ordered:
        an operator dying right after it resumes the abort from the
        label alone."""
        now = self.clock.now()
        need_capacity = 0
        if capacity is not None and capacity.has_signal:
            need_capacity = max(
                0, self.get_current_unavailable_nodes(state)
                - effective_budget)
            # Deadband: in the BENIGN regime (not paused, SLO intact)
            # tolerate an overshoot smaller than ~3% of the serving
            # fleet — demand noise moves the effective budget a few
            # nodes per pass, and aborting into that jitter churns
            # cordon/uncordon cycles for capacity the SLO headroom
            # already covers. A real collapse (peak pause, SLO
            # pressure) gets no band: its full excess aborts.
            status = capacity.last_status
            if not status["paused"] and not status["sloBreached"]:
                slack = max(1, status["servingNodes"] // 32)
                if need_capacity <= slack:
                    need_capacity = 0
        window = policy.maintenance_window
        predictor = self._predictor_for_policy(policy)
        close = None
        margin = 0.0
        if window is not None and window.enable and predictor is not None:
            close = window.close_at(now)
            margin = float(window.margin_seconds or 0)
        if need_capacity <= 0 and close is None:
            return
        for source in ABORTABLE_STATES:
            bucket = state.node_states.get(str(source), [])
            moved: list[NodeUpgradeState] = []
            for ns in bucket:
                reason = None
                if close is not None:
                    if now >= close:
                        reason = "window"
                    else:
                        remaining = predictor.remaining_seconds(
                            ns.node.metadata.name, str(source),
                            ns.node.metadata.annotations, now)
                        if now + remaining + margin > close:
                            reason = "window"
                if reason is None and need_capacity > 0:
                    reason = "capacity"
                if reason is None:
                    continue
                if self._obs is not None:
                    # recorded BEFORE the write attempt: the decision
                    # exists even if the commit defers, and the chaos
                    # monitor's edge audit never races a crash landing
                    # between the write and the record
                    self._obs.audit.record(
                        "abort", ns.node.metadata.name,
                        decision="abort", rule=reason,
                        inputs={
                            "source": str(source),
                            "needCapacity": need_capacity,
                            "effectiveBudget": effective_budget,
                            **({"closeAt": round(close, 1)}
                               if close is not None else {}),
                        })
                with self._defer_node_on_transient(ns.node,
                                                   "abort admit"):
                    if self.provider.change_node_upgrade_state(
                            ns.node, UpgradeState.ABORT_REQUIRED):
                        moved.append(ns)
                        if reason == "capacity":
                            need_capacity -= 1
                        if capacity is not None:
                            capacity.note_abort_started(
                                ns.node.metadata.name, now,
                                window=(reason == "window"))
                        if self.abort_audit is not None:
                            self.abort_audit("abort",
                                             ns.node.metadata.name,
                                             now, reason)
                        if self._policy_engine is not None:
                            # abort.audit observation hook (fail-open)
                            self._policy_engine.observe_abort(
                                "abort", ns.node.metadata.name,
                                now, reason)
                        logger.info(
                            "aborting mid-flight upgrade of node %s "
                            "(%s; was %s)", ns.node.metadata.name,
                            "capacity collapse" if reason == "capacity"
                            else "maintenance-window close", source)
            for ns in moved:
                bucket.remove(ns)
                state.node_states.setdefault(
                    str(UpgradeState.ABORT_REQUIRED), []).append(ns)

    def process_abort_required_nodes(
            self, state: ClusterUpgradeState) -> None:
        """Complete mid-flight aborts: halt eviction, release the
        serving-gate drain, uncordon, and return the node to
        ``upgrade-required`` with zero residue.

        Eviction is halted structurally — the node left the
        pod-deletion/drain buckets when it was admitted here, so no new
        worker is scheduled, and any ALREADY-in-flight async worker's
        outcome commit fails the provider's optimistic label
        precondition (abort-required != the drain-required it
        expects). The gate release is explicit and driven from the
        durable label (not the GateKeeper's in-memory parked record),
        so an operator that crashed mid-abort — fresh managers, empty
        GateKeeper — still returns the endpoints to admitting when it
        resumes. Ordering mirrors uncordon-required: the physical
        uncordon precedes the label commit (a failed uncordon leaves
        the node abort-required for retry), and every piece of upgrade
        bookkeeping (phase-start stamp, wait-for-jobs stamp, validation
        stamp) is deleted on the SAME merge patch as the commit —
        crash-atomic, no residue window."""
        def abort(ns: NodeUpgradeState) -> None:
            node = ns.node
            name = node.metadata.name
            pods = self.client.list_pods(
                namespace=None,
                field_selector=NODE_NAME_FIELD_SELECTOR_FMT.format(name))
            self.pod_manager.release_gate(node, pods)
            self.drain_manager.release_gate(node, pods)
            annotations: dict[str, Optional[str]] = {
                self.keys.phase_start_annotation: None,
                self.keys.pod_completion_start_annotation: None,
                self.keys.validation_start_annotation: None,
            }
            if self.keys.initial_state_annotation \
                    not in node.metadata.annotations:
                self.cordon_manager.uncordon(node)
            # else: the node was cordoned BEFORE the upgrade began —
            # the abort restores that state, so the cordon AND its
            # memory stay (the next admission re-enters with both)
            if self._obs is not None:
                self._obs.audit.record(
                    "aborted", name, decision="back-to-required",
                    rule="abort-complete", inputs={})
            if self.provider.change_node_upgrade_state(
                    node, UpgradeState.UPGRADE_REQUIRED,
                    annotations=annotations):
                now = self.clock.now()
                if self._capacity is not None:
                    self._capacity.note_abort_finished(name, now)
                if self.abort_audit is not None:
                    self.abort_audit("aborted", name, now, "")
                if self._policy_engine is not None:
                    self._policy_engine.observe_abort(
                        "aborted", name, now, "")
                logger.info(
                    "node %s abort complete: back to upgrade-required, "
                    "serving endpoints admitting", name)

        self._map_bucket(state.bucket(UpgradeState.ABORT_REQUIRED),
                         "abort", abort)

    def process_validation_required_nodes(
            self, state: ClusterUpgradeState) -> None:
        """Run the validation gate (upgrade_state.go:880-911)."""
        def validate(ns: NodeUpgradeState) -> None:
            # The runtime pod may have restarted after entering this
            # state and be blocked on safe load again
            # (upgrade_state.go:886-893).
            self.safe_load_manager.unblock_loading(ns.node)
            if not self.validation_manager.validate(ns.node):
                logger.info("validation not complete on node %s",
                            ns.node.metadata.name)
                return
            self._update_node_to_uncordon_or_done(ns.node)

        self._map_bucket(state.bucket(UpgradeState.VALIDATION_REQUIRED),
                         "validation", validate)

    def process_uncordon_required_nodes(
            self, state: ClusterUpgradeState) -> None:
        """Uncordon and finish (upgrade_state.go:915-934).

        The physical uncordon must come before the label write (a failed
        uncordon must leave the node in uncordon-required for retry, the
        reference's ordering) — but a STALE snapshot must not uncordon a
        node a faster pass already finished and a new rollout re-cordoned.
        Re-reading the label first closes that stale-pass window; the
        write itself still carries the optimistic-concurrency check.
        """
        def uncordon(ns: NodeUpgradeState) -> None:
            current = self.provider.get_node(ns.node.metadata.name) \
                .metadata.labels.get(self.keys.state_label, "")
            if current != str(UpgradeState.UNCORDON_REQUIRED):
                logger.warning(
                    "node %s is %r, not uncordon-required: snapshot "
                    "is stale; skipping uncordon",
                    ns.node.metadata.name, current or "unknown")
                return
            self.cordon_manager.uncordon(ns.node)
            if self.provider.change_node_upgrade_state(
                    ns.node, UpgradeState.DONE):
                self._count_slot_freed()

        self._map_bucket(state.bucket(UpgradeState.UNCORDON_REQUIRED),
                         "uncordon", uncordon)

    def _count_slot_freed(self) -> None:
        """A node reached DONE inside the current pass: its in-flight
        slot is free again (thread-safe — finish commits run on the
        bucket pool)."""
        with self._deferral_lock:
            self._pass_slots_freed += 1

    def _eager_slot_refill(self, state: ClusterUpgradeState,
                           policy: UpgradePolicySpec,
                           planner: UpgradePlanner,
                           max_unavailable: int,
                           max_parallel: Optional[int] = None,
                           capacity: "object" = None) -> None:
        """Re-spend slots freed by nodes that finished THIS pass.

        Admission runs first in ``apply_state`` (reference bucket
        order), so a slot freed by an uncordon later in the same pass
        used to sit idle until the next reconcile — the in-flight
        window drained by one wave-slot per finish, and a poll-paced
        consumer paid a full interval of lost parallelism for it. This
        second admission round runs after the finish buckets, against
        the nodes' CURRENT labels (provider commits update the node
        objects in place, so no cluster read is needed), and re-applies
        the exact same throttle math and planner — maxUnavailable,
        maxParallel, ICI-slice atomicity and the canary cohort all hold
        because they are re-derived, not cached.

        Candidates are restricted to nodes that BOTH started and still
        sit in ``upgrade-required``: a node idle-triaged into the queue
        this pass already made its one transition, and admitting it
        here would break the one-transition-per-pass invariant the
        chaos monitor audits. Halted fleets refill nothing — the freeze
        must also freeze this round."""
        with self._deferral_lock:
            freed = self._pass_slots_freed
        if freed <= 0 or self._rollout.halted:
            return
        if capacity is not None and capacity.budget_falling:
            # same admission hysteresis as the main round: refilling
            # into a contracting budget is churn (see apply_state)
            return
        required = str(UpgradeState.UPGRADE_REQUIRED)
        effective = ClusterUpgradeState()
        candidates: list[NodeUpgradeState] = []
        for label, bucket in state.node_states.items():
            for ns in bucket:
                current = ns.node.metadata.labels.get(
                    self.keys.state_label, "")
                effective.node_states.setdefault(current, []).append(ns)
                if current == required and label == required:
                    candidates.append(ns)
        if not candidates:
            return
        if max_parallel is None:
            max_parallel = policy.max_parallel_upgrades
        available = self.get_upgrades_available(
            effective, max_parallel, max_unavailable)
        if available <= 0:
            return
        effective.node_states[required] = candidates
        self.eager_refills_total += 1
        logger.info(
            "eager slot refill: %d slot(s) freed this pass, %d "
            "available, %d candidate(s)", freed, available,
            len(candidates))
        self.process_upgrade_required_nodes(effective, available,
                                            planner=planner)
        admitted = sum(
            1 for ns in candidates
            if ns.node.metadata.labels.get(self.keys.state_label, "")
            == str(UpgradeState.CORDON_REQUIRED))
        self.eager_refill_admissions_total += admitted
        if self.last_pass_slots is not None:
            self.last_pass_slots["refilled"] = admitted

    # ------------------------------------------------------------------
    # predicates
    # ------------------------------------------------------------------
    def _pod_in_sync_with_ds(self,
                             ns: NodeUpgradeState) -> tuple[bool, bool]:
        """(synced, orphaned) — orphaned pods are never "synced"
        (upgrade_state.go:552-578)."""
        if ns.is_orphaned():
            return False, True
        pod_hash = self.pod_manager.get_pod_revision_hash(ns.runtime_pod)
        ds_hash = self.pod_manager.get_daemon_set_revision_hash(
            ns.runtime_daemon_set)
        return pod_hash == ds_hash, False

    def _is_runtime_pod_in_sync(self, ns: NodeUpgradeState) -> bool:
        """Synced AND Running AND all containers ready
        (upgrade_state.go:936-964)."""
        synced, orphaned = self._pod_in_sync_with_ds(ns)
        if orphaned:
            return False
        return synced and ns.runtime_pod.is_ready()

    def _is_upgrade_requested(self, node: Node) -> bool:
        return node.metadata.annotations.get(
            self.keys.upgrade_requested_annotation) == TRUE_STRING

    def _skip_node_upgrade(self, node: Node) -> bool:
        return node.metadata.labels.get(
            self.keys.skip_label) == TRUE_STRING

    def _update_node_to_uncordon_or_done(self, node: Node) -> None:
        """Finish the node: uncordon-required normally, straight to done if
        it was already cordoned before the upgrade began
        (upgrade_state.go:1000-1028).

        On the DONE arc the initial-state marker deletion rides the
        state commit's merge patch (one write, crash-atomic): the
        "committed DONE but died before deleting the marker" crash
        residue the idle-triage paths mop up can no longer be minted by
        THIS path, and a stale snapshot still patches nothing — the
        provider's precondition covers label and annotation together.
        """
        new_state = UpgradeState.UNCORDON_REQUIRED
        annotation = self.keys.initial_state_annotation
        annotations = None
        if annotation in node.metadata.annotations:
            logger.info("node %s was unschedulable before upgrade; "
                        "skipping uncordon", node.metadata.name)
            new_state = UpgradeState.DONE
            annotations = {annotation: None}
        committed = self.provider.change_node_upgrade_state(
            node, new_state, annotations=annotations)
        if committed and new_state == UpgradeState.DONE:
            # the pre-cordoned arc finishes in place: its max-parallel
            # slot frees this pass even though availability is unchanged
            self._count_slot_freed()

    # ------------------------------------------------------------------
    # fleet counters (upgrade_state.go:188-211, 1034-1120)
    # ------------------------------------------------------------------
    def get_total_managed_nodes(self, state: ClusterUpgradeState) -> int:
        return sum(len(v) for v in state.node_states.values())

    def get_upgrades_in_progress(self, state: ClusterUpgradeState) -> int:
        return sum(len(state.bucket(s)) for s in IN_PROGRESS_STATES)

    def get_upgrades_done(self, state: ClusterUpgradeState) -> int:
        return len(state.bucket(UpgradeState.DONE))

    def get_upgrades_failed(self, state: ClusterUpgradeState) -> int:
        return len(state.bucket(UpgradeState.FAILED))

    def get_upgrades_pending(self, state: ClusterUpgradeState) -> int:
        return len(state.bucket(UpgradeState.UPGRADE_REQUIRED))

    def get_current_unavailable_nodes(self, state: ClusterUpgradeState) -> int:
        """Cordoned or not-ready nodes (upgrade_state.go:192-211)."""
        count = 0
        for bucket in state.node_states.values():
            for ns in bucket:
                if ns.node.is_unschedulable() or not ns.node.is_ready():
                    count += 1
        return count

    def get_upgrades_available(self, state: ClusterUpgradeState,
                               max_parallel_upgrades: int,
                               max_unavailable: int) -> int:
        """The throttle math (upgrade_state.go:1073-1102): parallel-slot
        budget intersected with the unavailability budget, where nodes
        already unavailable (cordoned/not-ready) and nodes about to be
        cordoned all count against maxUnavailable."""
        in_progress = self.get_upgrades_in_progress(state)
        total_nodes = self.get_total_managed_nodes(state)
        if max_parallel_upgrades == 0:
            available = len(state.bucket(UpgradeState.UPGRADE_REQUIRED))
        else:
            available = max_parallel_upgrades - in_progress

        unavailable = (self.get_current_unavailable_nodes(state)
                       + len(state.bucket(UpgradeState.CORDON_REQUIRED)))
        if available > max_unavailable:
            available = max_unavailable
        if unavailable >= max_unavailable:
            available = 0
        elif (max_unavailable < total_nodes
              and unavailable + available > max_unavailable):
            available = max_unavailable - unavailable
        # The reference can return a negative count here when in-progress
        # exceeds the parallel budget (upgrade_state.go:1084 with no clamp)
        # — harmless to its caller but wrong as an exposed fleet counter.
        return max(0, available)

    def cluster_status(self, state: ClusterUpgradeState) -> dict:
        """CRD-embeddable status block for one snapshot.

        Reference consumers surface the fleet counters
        (upgrade_state.go:1034-1120) in their own CRD ``.status``; this
        returns that block ready-made — JSON-serializable, camelCase
        keys, deterministic ordering — plus the TPU-native slice
        availability when topology labels are present.
        """
        # raw snapshot buckets, not ALL_STATES: a node with an unrecognized
        # label value must still appear (as its raw label) so the per-state
        # counts always sum to totalNodes
        per_state = {key or "unknown": len(bucket)
                     for key, bucket in state.node_states.items() if bucket}
        status = {
            "totalNodes": self.get_total_managed_nodes(state),
            "upgradesInProgress": self.get_upgrades_in_progress(state),
            "upgradesDone": self.get_upgrades_done(state),
            "upgradesFailed": self.get_upgrades_failed(state),
            "upgradesPending": self.get_upgrades_pending(state),
            "unavailableNodes": self.get_current_unavailable_nodes(state),
            "nodesByState": dict(sorted(per_state.items())),
        }
        nodes = state.all_nodes()
        from tpu_operator_libs.consts import GKE_TPU_TOPOLOGY_LABEL

        if any(GKE_TPU_TOPOLOGY_LABEL in n.metadata.labels for n in nodes):
            # only meaningful on TPU-labeled fleets: without topology
            # labels every node is its own "slice" and the number would
            # just restate node readiness; shares the snapshot's cached
            # topology with the planner instead of regrouping the fleet
            status["sliceAvailability"] = round(
                state.topology().availability(), 4)
        deferred = self.multislice_deferred_slices
        if deferred:
            # why the upgrade is pacing: these slices wait for a member
            # of their DCN job to come back up
            status["multisliceDeferredSlices"] = list(deferred)
        topology_block = self._topology_status(state, nodes)
        if topology_block:
            # the reconfiguration picture: spare-pool depth, bookings in
            # flight, and any slices admitted in a degraded shape —
            # derived from the snapshot alone, so every operator
            # incarnation reports the same truth
            status["topology"] = topology_block
        # per-node transitions deferred on transient cluster errors in
        # the MOST RECENT pass (after a chained reconcile: the count
        # still outstanding at chain exit) — a current-flakiness
        # signal; the status block is per snapshot, so the lifetime
        # total stays in _transient_deferrals for metrics/debugging
        if self.last_pass_deferrals:
            status["transientDeferrals"] = self.last_pass_deferrals
        rollout = self.rollout_guard.status()
        if rollout:
            # why the rollout is gated: canary wave in flight, or the
            # fleet halted on a quarantined revision
            status["rollout"] = rollout
        if self.last_pass_slots is not None:
            # in-flight window saturation + eager-refill evidence for
            # the most recent pass (why the fleet is / is not pacing)
            status["slots"] = dict(self.last_pass_slots)
        if self._predictive_planner is not None \
                and self._predictive_planner.last_plan is not None:
            # the predictive-planner ETA: learned-duration makespan
            # forecast, per-wave breakdown, and the maintenance-window
            # picture of the most recent plan
            planner_block = dict(self._predictive_planner.last_plan)
            planner_block["knownNodes"] = self._predictor.known_nodes
            planner_block["samplesTotal"] = self._predictor.samples_total
            status["planner"] = planner_block
        if self.last_preflight is not None:
            # the what-if picture: the most recent preflight forecast
            # (makespan bounds, per-class SLO risk, read-only
            # evidence) and the verdict the admission gate acted on
            status["preflight"] = dict(self.last_preflight)
        if self._capacity is not None \
                and self._capacity.last_status is not None:
            # the traffic-aware budget picture: live demand vs serving
            # capacity, the effective budget the throttle actually
            # spent, and the abort/SLO accounting
            status["capacity"] = dict(self._capacity.last_status)
            if self._cost_ranker is not None \
                    and self._cost_ranker.last_rank is not None:
                # the class-aware drain picture: per-tier candidate
                # counts and the sole-replica holds of the last plan
                ranker_block = dict(self._cost_ranker.last_rank)
                ranker_block["holds"] = {
                    node: rule for node, (rule, _)
                    in sorted(self._cost_ranker.last_holds.items())}
                status["capacity"]["ranker"] = ranker_block
            if self._prewarm is not None:
                status["capacity"]["prewarm"] = {
                    "reservationsTotal":
                        self._prewarm.reservations_total,
                    "readyTotal": self._prewarm.ready_total,
                    "releasedTotal": self._prewarm.released_total,
                }
        if self._policy_engine is not None \
                and self._policy_engine.active:
            # the declarative-policy picture: active hooks, eval/error/
            # budget counters, and this pass's policy holds — how the
            # sandboxed programs are steering (or parking) the fleet
            status["policy"] = self._policy_engine.status()
        if self._dag is not None and self._dag.active:
            # the multi-artifact DAG picture: per-artifact targets,
            # quarantines, and the stamp/advance/rollback accounting
            status["artifactDAG"] = self._dag.status()
        if self._shard_view is not None and self.last_shard_status:
            # the sharded-control-plane picture: which shards this
            # replica owns, the fleet-wide per-shard node census, and
            # the durable budget-share split the partition spends under
            shard_block: dict = {
                "identity": getattr(self._shard_view, "identity", ""),
                "owned": list(self.last_shard_status["owned"]),
                "numShards": self.last_shard_status["numShards"],
                "perShard": {
                    str(shard): dict(cell) for shard, cell in
                    sorted(self.last_shard_status["perShard"].items())},
            }
            if self.last_budget_shares is not None:
                shard_block["budgetShares"] = dict(
                    self.last_budget_shares)
            accounting = getattr(self.client, "read_accounting", None)
            if accounting is not None:
                # this replica's read-path cost picture: delegate
                # calls/objects, steady-state pod LISTs (0 is the
                # O(partition) claim), ingest keep/drop split, and the
                # snapshot build cost
                reads = accounting()
                if self.last_snapshot_build_seconds is not None:
                    reads["snapshotBuildSeconds"] = round(
                        self.last_snapshot_build_seconds, 6)
                shard_block["reads"] = reads
            status["shards"] = shard_block
        if self.nudger is not None:
            wakeups = self.nudger.counts_snapshot()
            if wakeups:
                # per-source wakeup counts (drain/eviction/validation-
                # timeout/canary-bake/…): the event-driven layer's
                # lifetime activity, matching observe_latency's counters
                status["wakeups"] = wakeups
        if self._obs is not None:
            # the journey-tracer roll-up: open/completed journeys,
            # outcome split, duration percentiles, the most recent
            # closed traces — cluster_status's answer to "what
            # happened to the nodes that did upgrade"
            trace_block = self._obs.tracer.summary()
            if trace_block:
                status["trace"] = trace_block
        return status

    def _topology_status(self, state: ClusterUpgradeState,
                         nodes: "list[Node]") -> dict:
        """Spare-pool / degraded-slice block for cluster_status (empty
        dict when neither exists — non-reconfiguring fleets see no new
        key)."""
        from tpu_operator_libs.topology.slice_topology import (
            decode_degraded_slices,
        )

        keys = self.topology_keys
        spares = [n for n in nodes
                  if n.metadata.labels.get(keys.spare_pool_label)
                  == TRUE_STRING]
        reserved = sum(1 for n in spares
                       if keys.reserved_for_annotation
                       in n.metadata.annotations)
        degraded: dict[str, tuple[str, ...]] = {}
        seen_ds: set[str] = set()
        for bucket in state.node_states.values():
            for ns in bucket:
                ds = ns.runtime_daemon_set
                if ds is None or ds.metadata.uid in seen_ds:
                    continue
                seen_ds.add(ds.metadata.uid)
                degraded.update(decode_degraded_slices(
                    ds.metadata.annotations.get(
                        keys.degraded_slices_annotation, "")))
        out: dict = {}
        if spares:
            out["sparePool"] = {"size": len(spares), "inUse": reserved}
        if degraded:
            out["degradedSlices"] = {
                sid: list(hosts) for sid, hosts in sorted(degraded.items())}
        return out

    # ------------------------------------------------------------------
    # explain (obs/ public API)
    # ------------------------------------------------------------------
    def explain(self, node_name: str) -> dict:
        """Why is this node not upgrading — and what happened to it?

        Returns ``{"node", "state", "blocking": [reason, ...],
        "records": [...], "trace": [...]}``: the current
        blocking-reason chain (ordered outermost rule first), the
        node's recent DecisionAudit records, and its recent journey
        spans. Everything is answered from in-memory state (the last
        snapshot, the audit ring, the tracer) — no cluster read, so it
        cannot fail on an apiserver fault, and it works on whatever
        the operator last knew even mid-incident.

        Under sharding the query routes: a node owned by another
        replica's shard is forwarded through
        ``observability.peer_resolver`` when one is installed (the
        owning replica's audit has the records); otherwise the local
        answer is derived from durable node state alone and marked
        with the owning shard — which is also the handover story: a
        dead owner's ring buffer is gone, but the label + stamps are
        not, so the chain is never empty (pinned by the handover
        regression in tests/test_obs.py).
        """
        out: dict = {"node": node_name}
        obs = self._obs
        view = self._shard_view
        if view is not None:
            entry = self._census_entry(node_name)
            shard = entry[0] if entry is not None else None
            if shard is None:
                pool = None
                state = self._last_full_state or self.last_state
                if state is not None:
                    for bucket in state.node_states.values():
                        for ns in bucket:
                            if ns.node.metadata.name == node_name:
                                pool = self._node_pool(ns.node)
                                break
                if pool is None:
                    # a mid-restart node on another partition may be
                    # absent from the snapshot — one guarded (usually
                    # cached) node read resolves its pool for ROUTING
                    # only; on any fault the local fallback below
                    # still answers from what this replica knows
                    try:
                        pool = self._node_pool(
                            self.client.get_node(node_name))
                    except Exception:  # noqa: BLE001 — explain must
                        pool = None  # answer, not raise, mid-incident
                if pool is not None and hasattr(view, "ring"):
                    shard = view.ring.shard_for(node_name, pool)
            if shard is not None and shard not in view.owned_shards():
                out["ownedByShard"] = shard
                out["local"] = False
                resolver = getattr(obs, "peer_resolver", None)
                peer = None
                route_failed = False
                if resolver is not None:
                    try:
                        peer = resolver(shard)
                    except Exception:  # noqa: BLE001 — routing must
                        peer = None  # not break the local answer
                if peer is not None:
                    routed = self._routed_explain(peer, node_name)
                    if routed is not None:
                        routed["routedVia"] = shard
                        return routed
                    route_failed = True
                out.update(self._explain_local(node_name))
                if route_failed:
                    out["blocking"].insert(
                        0, f"owning replica (shard {shard}) did not "
                        f"answer within the peer timeout: answer "
                        f"derived from durable node state instead of "
                        f"stalling the request")
                else:
                    out["blocking"].insert(
                        0, f"owned by shard {shard} (not this "
                        f"replica): answer derived from durable node "
                        f"state; query the owning replica's /explain "
                        f"for its audit ring")
                return out
        out.update(self._explain_local(node_name))
        return out

    def _routed_explain(self, peer: "object",
                        node_name: str) -> "Optional[dict]":
        """One bounded cross-replica explain hop: the peer is an HTTP
        call away in production, and a slow or dead owning replica
        must degrade this request to the durable-label fallback, not
        stall it — explain is the mid-incident tool, and the incident
        may be exactly what made the peer slow. Each attempt runs on a
        daemon worker bounded by ``obs.peer_timeout_seconds`` REAL
        seconds (an RPC bound, never the virtual clock), with
        ``obs.peer_retries`` retries; a hung attempt's thread is
        abandoned to finish in the background. Returns None when every
        attempt failed or timed out (caller falls back)."""
        import threading

        obs = self._obs
        timeout = max(0.05, float(getattr(obs, "peer_timeout_seconds",
                                          2.0)))
        retries = max(0, int(getattr(obs, "peer_retries", 1)))
        for attempt in range(1 + retries):
            box: dict = {}
            done = threading.Event()

            def hop(box: dict = box, done: "threading.Event" = done,
                    ) -> None:
                try:
                    box["value"] = peer.explain(node_name)
                except Exception as exc:  # noqa: BLE001 — peer fault
                    box["error"] = exc  # = fallback, never a raise
                finally:
                    done.set()

            worker = threading.Thread(
                target=hop, daemon=True,
                name=f"explain-peer-hop-{node_name}-{attempt}")
            worker.start()
            if done.wait(timeout) and "value" in box \
                    and isinstance(box["value"], dict):
                return box["value"]
            logger.warning(
                "peer explain for %s attempt %d/%d %s; %s",
                node_name, attempt + 1, 1 + retries,
                "failed" if done.is_set() else
                f"timed out after {timeout:g}s",
                "retrying" if attempt < retries
                else "falling back to durable node state")
        return None

    def _explain_local(self, node_name: str) -> dict:
        from tpu_operator_libs.upgrade.predictor import (
            PHASE_OF_STATE,
            _parse_stamp,
        )

        obs = self._obs
        out: dict = {"blocking": []}
        chain: list[str] = out["blocking"]
        # under sharding prefer the unfiltered snapshot: a routed (or
        # fallback) explain for a node outside this partition must
        # still see its labels/annotations
        state = self._last_full_state or self.last_state
        node = None
        label = None
        if state is not None:
            for bucket_label, bucket in state.node_states.items():
                for ns in bucket:
                    if ns.node.metadata.name == node_name:
                        node = ns.node
                        label = bucket_label
                        break
                if node is not None:
                    break
        if node is None:
            chain.append(
                "node not in the last snapshot (no snapshot built yet "
                "this incarnation, node vanished, or it is outside "
                "the managed selector)")
            out["state"] = "unknown"
        else:
            label = node.metadata.labels.get(
                self.keys.state_label, label or "")
            out["state"] = label or "unknown"
            annotations = node.metadata.annotations
            done = str(UpgradeState.DONE)
            required = str(UpgradeState.UPGRADE_REQUIRED)
            tk = self.topology_keys
            at_risk_at = annotations.get(
                f"{tk.domain}/{tk.driver}-remediation.at-risk-at")
            if at_risk_at:
                reason = annotations.get(
                    f"{tk.domain}/{tk.driver}-remediation.at-risk-reason",
                    "unknown signal")
                chain.append(
                    f"condemned at-risk at {at_risk_at} by the "
                    f"failure-precursor model ({reason}): slice "
                    f"remapping to a spare while the node still "
                    f"serves; it leaves service as a planned, gated "
                    f"drain once released")
            if node.metadata.labels.get(self.keys.skip_label) \
                    == TRUE_STRING:
                chain.append(f"skip label {self.keys.skip_label} set: "
                             f"node opted out of upgrades")
            if label == done:
                if not chain:
                    chain.append("upgrade complete — nothing blocking")
            elif label in ("", required):
                self._explain_parked(chain, node, annotations)
            elif label == str(UpgradeState.FAILED):
                chain.append(
                    "parked in upgrade-failed (validation timeout or "
                    "unrecoverable pod) — waiting for remediation, "
                    "rollback, or manual repair")
                condemned = self.topology_keys
                rem_note = annotations.get(
                    f"{condemned.domain}/{condemned.driver}"
                    "-remediation.condemned-at")
                if rem_note:
                    chain.append(f"condemned at {rem_note} — slice "
                                 f"reconfiguration may be in flight")
            else:
                phase = PHASE_OF_STATE.get(label)
                detail = f"mid-flight: {label}"
                stamp_phase, stamp_at = _parse_stamp(
                    annotations.get(self.keys.phase_start_annotation))
                if stamp_phase is not None:
                    elapsed = max(0.0, self.clock.now() - stamp_at)
                    detail += (f" ({stamp_phase} phase, "
                               f"{elapsed:.0f}s elapsed")
                    if self._predictor is not None and phase is not None:
                        remaining = self._predictor.remaining_seconds(
                            node_name, label, annotations,
                            self.clock.now())
                        detail += f", ~{remaining:.0f}s predicted left"
                    detail += ")"
                chain.append(detail)
                if label == str(UpgradeState.VALIDATION_REQUIRED) \
                        and self._dag is not None and self._dag.active:
                    pending = self._dag.incomplete_artifacts(node)
                    if pending:
                        chain.append(
                            f"artifact DAG advancing in this node's "
                            f"cordon cycle: waiting on "
                            f"{', '.join(pending)} (dependency order)")
        if obs is not None:
            records = obs.audit.records_for(node_name, limit=10)
            out["records"] = [rec.as_dict() for rec in records]
            fleet = obs.audit.latest_fleet()
            if fleet:
                out["fleet"] = {kind: rec.as_dict()
                                for kind, rec in sorted(fleet.items())}
            trace = obs.tracer.spans_for(node_name)
            if trace:
                out["trace"] = trace
        if not chain:
            # structurally unreachable for a parked node, but explain
            # must NEVER answer with silence — that is the artifact
            # gap this layer exists to close
            chain.append(f"state {out.get('state')!r}: no blocking "
                         f"rule derived; see records")
        return out

    def _explain_parked(self, chain: "list[str]", node: Node,
                        annotations: "dict[str, str]") -> None:
        """The blocking chain for a node sitting in upgrade-required /
        unknown: outermost gate first, derived from the same pass state
        the admission decisions read."""
        obs = self._obs
        name = node.metadata.name
        if self._rollout.halted:
            chain.append(
                f"fleet halted: revision(s) "
                f"{sorted(self._rollout.quarantined)} quarantined — "
                f"no admissions until rollback completes")
        elif self._rollout.canary_active \
                and name not in self._rollout.cohort:
            chain.append(
                f"canary wave in flight ({len(self._rollout.cohort)} "
                f"cohort node(s)): admissions restricted to the "
                f"cohort until the bake passes")
        preflight = self.last_preflight
        if preflight is not None and preflight.get("verdict") == "reject":
            makespan = preflight.get("makespan", {})
            risk = preflight.get("sloRisk", {})
            chain.append(
                f"preflight rejected the rollout "
                f"({', '.join(preflight.get('breaches', []))}): "
                f"forecast makespan <= "
                f"{makespan.get('upperSeconds')}s at "
                f"{makespan.get('confidence')} confidence, worst SLO "
                f"risk {risk.get('worstFraction', 0.0)} on class "
                f"{risk.get('worstClass', 'fleet')!r} — admissions "
                f"parked until the forecast clears")
        ranker = self._cost_ranker
        if ranker is not None and name in ranker.last_holds:
            rule, hold_inputs = ranker.last_holds[name]
            chain.append(
                f"held by disruption-cost ranker: {rule} — draining "
                f"would leave model {hold_inputs.get('model')!r} "
                f"(class {hold_inputs.get('class')}) below its "
                f"replication floor; prewarm arc: "
                f"{hold_inputs.get('prewarm')}")
        engine = self._policy_engine
        if engine is not None and name in engine.last_holds:
            rule, detail = engine.last_holds[name]
            detail = detail or ("the declarative admission program "
                                "denied the candidate")
            chain.append(f"held by policy hook: {rule} — {detail}")
        latest = obs.audit.records_for(name, limit=5) \
            if obs is not None else []
        for rec in latest:
            if rec.kind == "window" and rec.decision == "defer":
                chain.append(
                    f"maintenance window: predicted completion "
                    f"t={rec.inputs.get('predictedDone')} crosses the "
                    f"close — deferred untouched")
                break
            if rec.kind == "hold":
                chain.append(f"held by planner: {rec.rule} "
                             f"(slots={rec.inputs.get('slots')})")
                break
            if rec.kind in ("admit", "aborted"):
                break
        slots = self.last_pass_slots
        if slots is not None and slots.get("available", 0) <= 0:
            chain.append(
                f"no admission slots at the last pass: "
                f"{slots['inProgress']} in flight / budget "
                f"{slots['budget']}")
        capacity = self._capacity
        if capacity is not None and capacity.last_status is not None:
            status = capacity.last_status
            if status.get("paused"):
                chain.append(
                    "admission paused: serving utilization at peak "
                    f"(demand {status.get('demand')} vs capacity "
                    f"{status.get('capacityAvailable')})")
            elif getattr(capacity, "budget_falling", False):
                chain.append(
                    "admission frozen: effective budget falling "
                    "(traffic ramp in progress)")
        deferred = self.multislice_deferred_slices
        if deferred and self._node_pool(node) in deferred:
            chain.append(
                f"slice {self._node_pool(node)} deferred: its DCN "
                f"job's member budget is exhausted")
        if not chain:
            chain.append(
                "waiting in upgrade-required: eligible for the next "
                "admission wave (no gate currently blocks it)")

    # ------------------------------------------------------------------
    # chained reconcile
    # ------------------------------------------------------------------
    def reconcile(self, namespace: str, runtime_labels: dict[str, str],
                  policy: Optional[UpgradePolicySpec],
                  max_chain: int = 12) -> Optional[ClusterUpgradeState]:
        """build_state + apply_state, chained until node states stabilize.

        The reference moves a node at most one transition per reconcile and
        then waits for the operator's next reconcile interval, so a node
        burns ~interval seconds per edge of the state graph even when every
        action is instantaneous. Chaining is exactly what a consumer's
        immediate-requeue loop does — each inner pass is a full
        reference-semantics pass committed to node labels, preserving
        idempotence and crash-resume — minus the dead time. Stops as soon
        as a pass changes nothing (async work in flight reports through
        labels on a later reconcile), after ``max_chain`` passes, or when
        the snapshot is momentarily incomplete.

        Returns the last built state (None if the first build failed).
        """
        last_state = None
        fingerprint = None
        node_selector = (getattr(policy, "node_selector", "")
                         if policy is not None else "")
        for _ in range(max_chain):
            try:
                state = self.build_state(namespace, runtime_labels,
                                         node_selector)
            except BuildStateError:
                # restarted runtime pod between deletion and recreation;
                # nothing more to do until the controller catches up
                return last_state
            # The fingerprint must cover EVERY durable bit a pass can
            # write, not just the state label: a pass that only consumes
            # an annotation (upgrade-requested, safe-load, wait-start
            # stamps) or only flips unschedulable would otherwise look
            # like quiescence and end the chain one transition early.
            # Today every such path also moves a label, but that is an
            # accident of the current graph — this makes it structural.
            annotation_prefix = f"{self.keys.domain}/{self.keys.driver}-"
            new_fingerprint = tuple(sorted(
                (ns.node.metadata.name, label,
                 ns.node.is_unschedulable(),
                 tuple(sorted(
                     (key, value) for key, value
                     in ns.node.metadata.annotations.items()
                     if key.startswith(annotation_prefix))))
                for label, bucket in state.node_states.items()
                for ns in bucket))
            if new_fingerprint == fingerprint:
                return state
            fingerprint = new_fingerprint
            last_state = state
            self.apply_state(state, policy)
        return last_state

    # ------------------------------------------------------------------
    # test/sim helper
    # ------------------------------------------------------------------
    def join_workers(self, timeout: float = 30.0) -> None:
        """Wait for in-flight async drain/eviction workers and drain the
        bucket pool — the deterministic shutdown barrier tests, the
        simulator and crash-restart replays synchronize on."""
        self.drain_manager.join(timeout)
        self.pod_manager.join(timeout)
        if self._pool is not None:
            self._pool.drain(timeout)
