"""ValidationManager: the post-upgrade gate before a node returns to service.

Reference: validation_manager.go:35-175 — a validation pod, selected by
``pod_selector`` on the node, must be Running+Ready; if it stays not-ready
past a 600 s timeout (checkpointed in a node annotation) the node is marked
upgrade-failed.

TPU extension: an optional ``extra_validator`` callable is consulted after
the pod gate. This is the insertion point SURVEY.md §5 calls for — the ICI
fabric health probe (tpu_operator_libs.health.ici_probe) plugs in here so a
node only returns to service when the TPU interconnect is provably healthy.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Callable, Optional

from tpu_operator_libs.consts import UpgradeState
from tpu_operator_libs.k8s.client import K8sClient
from tpu_operator_libs.k8s.objects import Node
from tpu_operator_libs.upgrade.state_provider import NodeUpgradeStateProvider
from tpu_operator_libs.util import Clock, Event, EventRecorder, log_event

if TYPE_CHECKING:
    from tpu_operator_libs.upgrade.nudger import ReconcileNudger

logger = logging.getLogger(__name__)

VALIDATION_TIMEOUT_SECONDS = 600  # validation_manager.go:31-33

#: Re-check cadence for a failing EXTRA validator (seconds). A not-ready
#: validation pod becoming Ready is a watch event and wakes the loop on
#: its own; an extra validator (e.g. the ICI fabric probe) is invisible
#: to the watch stream, so without a timed retry its eventual pass would
#: only be discovered at the next resync. Registered through the nudger's
#: timer wheel, so a wave of probing nodes coalesces into one wakeup.
VALIDATION_RETRY_SECONDS = 15.0

#: Extra health gate: returns True when the node is healthy. Exceptions are
#: treated as "not yet healthy" and retried next reconcile.
NodeValidator = Callable[[Node], bool]


class ValidationManager:
    def __init__(self, client: K8sClient,
                 provider: NodeUpgradeStateProvider,
                 pod_selector: str = "",
                 recorder: Optional[EventRecorder] = None,
                 clock: Optional[Clock] = None,
                 extra_validator: Optional[NodeValidator] = None,
                 timeout_seconds: int = VALIDATION_TIMEOUT_SECONDS,
                 nudger: Optional["ReconcileNudger"] = None,
                 retry_seconds: float = VALIDATION_RETRY_SECONDS) -> None:
        self._client = client
        self._provider = provider
        self._pod_selector = pod_selector
        self._recorder = recorder
        self._clock = clock or Clock()
        self._extra_validator = extra_validator
        self._timeout_seconds = timeout_seconds
        self.nudger = nudger
        self.retry_seconds = retry_seconds
        self._keys = provider.keys
        #: Policy-engine seam (policy/engine.py), re-pointed by the
        #: state manager every pass: ``fn(node) -> None`` (pass),
        #: ``"policy-verdict"`` (unhealthy — runs the normal timeout
        #: ladder exactly like a failing extra validator) or
        #: ``"policy-park"`` (the program itself failed or overran its
        #: budget — the node PARKS in validation with no timer, audited
        #: by the engine, so a bad policy can delay but never
        #: fail/wedge a node). The DAG coordinator's completion gate
        #: rides the same seam with park semantics.
        self.policy_validator: Optional[Callable[[Node], Optional[str]]] \
            = None

    @property
    def pod_selector(self) -> str:
        return self._pod_selector

    def validate(self, node: Node) -> bool:
        """True when validation is complete for the node
        (validation_manager.go:71-116).

        Empty selector and no extra validator ⇒ trivially true (matches the
        reference's early return at :72-74). A not-ready validation pod (or
        failing extra validator) starts/checks the timeout; expiry flips the
        node to upgrade-failed.
        """
        if not self._pod_selector and self._extra_validator is None \
                and self.policy_validator is None:
            return True  # trivially valid, no annotation traffic (:72-74)

        failure = self._gate_failure(node)
        if failure is None:
            # Validation complete: clear the timeout stamp.
            self._provider.change_node_upgrade_annotation(
                node, self._keys.validation_start_annotation, None)
            return True
        if failure == "no-pods":
            # Missing validation pods never start the timer (matches the
            # reference's bare return at validation_manager.go:98-103).
            logger.warning("no validation pods found on node %s",
                           node.metadata.name)
            return False
        if failure == "policy-park":
            # The policy program itself failed/overran (or the artifact
            # DAG is still advancing): PARK — no failure timer. The
            # engine/coordinator already audited why; progress comes
            # from fixing the policy (or the DS controller), liveness
            # from the chaos gate's convergence check.
            if self.nudger is not None:
                self.nudger.nudge_after(self.retry_seconds,
                                        "validation-retry")
            return False
        if failure in ("extra-validator", "policy-verdict") \
                and self.nudger is not None:
            # the probe's eventual pass emits no cluster event — poll it
            # on the timer wheel instead of waiting for the resync
            self.nudger.nudge_after(self.retry_seconds,
                                    "validation-retry")
        self._handle_timeout(node, failure)
        return False

    def check(self, node: Node) -> bool:
        """Side-effect-free variant of :meth:`validate`: runs the same
        gates but never stamps/advances the timeout state machine. Used by
        failed-node recovery, which must consult the gate repeatedly
        without churning annotations or re-marking an already-failed
        node."""
        return self._gate_failure(node) is None

    def _gate_failure(self, node: Node) -> Optional[str]:
        """Evaluate both gates without side effects. Returns None when the
        node passes, else why it failed: "no-pods" (selector matched
        nothing), "pod-not-ready", or "extra-validator"."""
        if self._pod_selector:
            pods = self._client.list_pods(
                namespace=None, label_selector=self._pod_selector,
                field_selector=f"spec.nodeName={node.metadata.name}")
            if not pods:
                return "no-pods"
            if any(not pod.is_ready() for pod in pods):
                return "pod-not-ready"
        if self._extra_validator is not None:
            try:
                healthy = self._extra_validator(node)
            except Exception as exc:  # noqa: BLE001 — gate boundary
                logger.warning("extra validator raised on node %s: %s",
                               node.metadata.name, exc)
                healthy = False
            if not healthy:
                return "extra-validator"
        if self.policy_validator is not None:
            try:
                verdict = self.policy_validator(node)
            except Exception as exc:  # noqa: BLE001 — the sandbox
                # boundary's boundary: even a broken seam parks
                # instead of wedging the pass
                logger.warning("policy validator raised on node %s "
                               "(parking): %s", node.metadata.name, exc)
                verdict = "policy-park"
            if verdict:
                return verdict
        return None

    def _handle_timeout(self, node: Node,
                        reason: str = "unknown") -> None:
        """Start or check the validation timer (validation_manager.go:
        139-175): first failure stamps the start time; expiry marks the node
        upgrade-failed and clears the stamp. ``reason`` is the concrete
        gate failure ("pod-not-ready" / "extra-validator") carried into
        the Kubernetes Event, so operators watching ``kubectl get
        events`` see WHAT failed, not just that something did."""
        annotation = self._keys.validation_start_annotation
        now = int(self._clock.now())
        stamp = node.metadata.annotations.get(annotation)
        if stamp is None:
            self._provider.change_node_upgrade_annotation(
                node, annotation, str(now))
            if self.nudger is not None:
                # precise wakeup at expiry: the timeout otherwise fires
                # only when something else happens to run a pass
                self.nudger.nudge_at(now + self._timeout_seconds,
                                     "validation-timeout")
            return
        start = int(stamp)
        if self.nudger is not None and now <= start + self._timeout_seconds:
            # re-register on every sighting: idempotent through the
            # wheel's slot dedup, and it survives operator restarts
            # (the stamp is durable, the wheel is not)
            self.nudger.nudge_at(start + self._timeout_seconds,
                                 "validation-timeout")
        if now > start + self._timeout_seconds:
            committed = False
            try:
                committed = self._provider.change_node_upgrade_state(
                    node, UpgradeState.FAILED)
            except Exception as exc:  # noqa: BLE001 — matches reference's
                # ignored error at validation_manager.go:163
                logger.error("failed to fail node %s: %s",
                             node.metadata.name, exc)
            if not committed:
                # write failed or snapshot was stale (a concurrent pass
                # already moved the node on): the node was NOT marked
                # failed, so no event claiming otherwise and no stamp
                # cleanup — whatever state the node is really in owns
                # the stamp's lifecycle now
                return
            logger.info("validation timeout exceeded on node %s (%s)",
                        node.metadata.name, reason)
            log_event(self._recorder, node, Event.WARNING,
                      self._keys.event_reason,
                      f"Validation timed out after "
                      f"{self._timeout_seconds}s ({reason}); node marked "
                      f"upgrade-failed")
            self._provider.change_node_upgrade_annotation(
                node, annotation, None)
