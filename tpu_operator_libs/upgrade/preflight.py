"""Rollout preflight: what-if forecasting that gates admission.

Before the throttle spends slot one of a rollout, the
:class:`PreflightForecaster` answers "what would this rollout do to the
fleet if admitted NOW?" — entirely in-process, entirely read-only:

* the live cluster picture is cloned into a **frozen**
  :class:`~tpu_operator_libs.k8s.fake.FakeCluster` snapshot
  (``snapshot``/``freeze`` — every mutating call on the clone raises
  :class:`~tpu_operator_libs.k8s.fake.FrozenClusterError` and bumps a
  tripwire counter), so the forecast provably cannot write;
* the proposed wave is replayed ANALYTICALLY against the learned
  :class:`~tpu_operator_libs.upgrade.predictor.PhaseDurationPredictor`
  — the same LPT multiprocessor packing the predictive planner's
  ``_eta`` uses — yielding an expected makespan with confidence bounds
  from the predictor's retained forecast-error histogram;
* the capacity/traffic picture (live controller status, or a diurnal
  trace in soaks/benches) is swept across the forecast horizon for
  per-traffic-class SLO risk, expected mid-flight aborts and
  peak-pause ticks;
* the declarative policy hooks (``planner.admission`` /
  ``window.gate``) are evaluated against a FRESH
  :class:`~tpu_operator_libs.policy.engine.PolicyEngine` — forecast
  holds are counted without polluting the live engine's pass state;
* the maintenance window is applied with the conservative bound, so
  forecast window deferrals match what the planner would actually do.

The forecast is a plain JSON-able dict; ``verdict`` is the admission
gate: a ``required``-mode policy whose forecast breaches
``maxForecastSloRiskFraction`` or ``maxForecastMakespanSeconds`` parks
the rollout (zero slots spent) under an audited ``preflight-rejected``
rule until the picture improves. ``advisory`` mode records the breach
and admits anyway; ``off`` never builds a forecaster.

Crash safety is structural: the forecast path owns no durable state
and writes nothing, so an operator crash mid-forecast (the optional
``guard`` hook is the chaos harness's crash-fuse seam) leaves ZERO
residue — the next incarnation re-derives the identical forecast from
the same snapshot inputs.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Callable, Optional

from tpu_operator_libs.consts import IN_PROGRESS_STATES, UpgradeState
from tpu_operator_libs.util import Clock

if TYPE_CHECKING:  # pragma: no cover - annotation-only imports
    from tpu_operator_libs.api.upgrade_policy import (
        PreflightSpec,
        UpgradePolicySpec,
    )
    from tpu_operator_libs.upgrade.capacity import CapacityBudgetController
    from tpu_operator_libs.upgrade.predictor import PhaseDurationPredictor
    from tpu_operator_libs.upgrade.state_manager import ClusterUpgradeState

logger = logging.getLogger(__name__)

#: FakeCluster operations that mutate apiserver state — the live-side
#: evidence set: a preflight pass diffs the LIVE cluster's per-op call
#: counts over these before/after forecasting, and any delta is a
#: read-only-guarantee violation (the frozen-clone tripwire covers the
#: clone side; this covers "the forecaster wrote around the clone").
MUTATING_OPS = frozenset((
    "patch_node_labels", "patch_node_annotations", "patch_node_meta",
    "set_node_unschedulable", "delete_pod", "evict_pod",
    "create_event", "patch_event", "rollback_daemon_set",
    "patch_daemon_set_annotations",
))

#: Ticks swept across the forecast horizon for the SLO-risk replay —
#: fixed so the forecast is deterministic in its inputs (no wall-clock
#: dependent step sizing).
REPLAY_TICKS = 64

VERDICT_ADMIT = "admit"
VERDICT_ADVISORY = "advisory-breach"
VERDICT_REJECT = "reject"


def _mutation_count(counts: "dict[str, int]") -> int:
    return sum(n for op, n in counts.items() if op in MUTATING_OPS)


class PreflightForecaster:
    """One persistent forecaster per state manager / federation
    controller (mirrors the ``_capacity_for_policy`` lifecycle: created
    on first use from a policy with ``preflight.mode != off``, knobs
    refreshed every pass).

    ``trace`` (optional) is a diurnal utilization source — any object
    with ``utilization(now) -> float`` — used when no live capacity
    status exists or when the caller wants the forecast swept against a
    known traffic shape (soaks, benches, federation). ``classify``
    (optional) maps a node name to its traffic-class name so per-class
    timeline segments use real node shares. ``guard`` (optional) is
    called with ``"preflight-forecast"`` at the top of every computed
    forecast — the chaos harness wires the crash fuse here to prove
    crash-mid-forecast leaves no residue. ``live_call_counts``
    (optional) returns the LIVE cluster's per-op API call counts; the
    forecaster diffs :data:`MUTATING_OPS` across the forecast to
    evidence the read-only guarantee from the live side too.
    """

    def __init__(self, spec: "PreflightSpec", keys: "object",
                 predictor: "Optional[PhaseDurationPredictor]" = None,
                 clock: Optional[Clock] = None,
                 trace: "Optional[object]" = None,
                 classify: "Optional[Callable[[str], str]]" = None,
                 guard: "Optional[Callable[[str], None]]" = None,
                 live_call_counts:
                 "Optional[Callable[[], dict]]" = None) -> None:
        self.spec = spec
        self.keys = keys
        self.predictor = predictor
        self._clock = clock or Clock()
        self.trace = trace
        self.classify = classify
        self.guard = guard
        self.live_call_counts = live_call_counts
        #: Most recent forecast dict (cluster_status / HTTP feed).
        self.last_forecast: Optional[dict] = None
        #: Lifetime computed forecasts (cache misses).
        self.forecasts_total = 0
        #: Lifetime forecasts served from the single-entry cache.
        self.cache_hits_total = 0
        #: Lifetime required-mode rejections.
        self.rejected_total = 0
        #: Lifetime advisory-mode breaches.
        self.advisory_total = 0
        #: Lifetime write attempts that reached a frozen clone (any
        #: nonzero is a read-only-guarantee violation — invariant feed).
        self.frozen_write_attempts_total = 0
        #: Lifetime live-cluster mutations observed during a forecast
        #: (any nonzero is a violation — invariant feed).
        self.live_mutations_total = 0
        self._cache_key: "Optional[tuple]" = None

    # ------------------------------------------------------------------
    # spec lifecycle
    # ------------------------------------------------------------------
    def refresh(self, spec: "PreflightSpec") -> None:
        """Policy re-read every pass (reference semantics): knob
        changes take effect without dropping counters or cache."""
        if spec is not self.spec:
            self.spec = spec

    # ------------------------------------------------------------------
    # forecast
    # ------------------------------------------------------------------
    def forecast(self, state: "ClusterUpgradeState",
                 policy: "UpgradePolicySpec",
                 slots: Optional[int] = None,
                 capacity: "Optional[CapacityBudgetController]" = None,
                 now: Optional[float] = None) -> dict:
        """The what-if forecast for admitting the pending rollout now.

        ``slots`` is the in-flight window the throttle would actually
        spend (the pass's ``upgrades_available``); when omitted it is
        derived from the policy's static budget. Returns the forecast
        dict (also retained as :attr:`last_forecast`); never raises on
        model cold start — a forecast with zero error samples carries
        the documented cold-start spread instead.
        """
        if now is None:
            now = self._clock.now()
        pending = [ns for ns in state.bucket(UpgradeState.UPGRADE_REQUIRED)]
        in_progress = [(str(bucket_state), ns)
                       for bucket_state in IN_PROGRESS_STATES
                       for ns in state.bucket(bucket_state)]
        if slots is None:
            slots = self._static_slots(state, policy, len(pending))
        key = self._cache_lookup_key(policy, pending, in_progress,
                                     slots, now)
        if key is not None and key == self._cache_key \
                and self.last_forecast is not None:
            self.cache_hits_total += 1
            return self.last_forecast

        if self.guard is not None:
            # the chaos harness's crash-fuse seam: a fuse armed for
            # "preflight-forecast" raises OperatorCrash HERE — before
            # any result is retained, after zero writes
            self.guard("preflight-forecast")

        live_before = (dict(self.live_call_counts())
                       if self.live_call_counts is not None else None)
        clone = self._frozen_clone(state, now)
        try:
            forecast = self._compute(clone, state, policy, pending,
                                     in_progress, slots, now, capacity)
        finally:
            attempts = getattr(clone, "frozen_write_attempts", 0)
            self.frozen_write_attempts_total += attempts
        live_mutations = 0
        if live_before is not None:
            live_after = dict(self.live_call_counts())
            live_mutations = max(
                0, _mutation_count(live_after)
                - _mutation_count(live_before))
            self.live_mutations_total += live_mutations
        forecast["readonly"] = {
            "frozenWriteAttempts": attempts,
            "liveMutations": live_mutations,
        }
        self.forecasts_total += 1
        if forecast["verdict"] == VERDICT_REJECT:
            self.rejected_total += 1
        elif forecast["verdict"] == VERDICT_ADVISORY:
            self.advisory_total += 1
        self.last_forecast = forecast
        self._cache_key = key
        return forecast

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _static_slots(self, state: "ClusterUpgradeState",
                      policy: "UpgradePolicySpec", n_pending: int) -> int:
        """Standalone-mode slot derivation (HTTP / federation / bench
        callers without a live pass): the static parallel budget
        intersected with maxUnavailable, never below 1 while work
        remains."""
        from tpu_operator_libs.api.upgrade_policy import (
            scaled_value_from_int_or_percent,
        )

        total = sum(len(bucket) for bucket in state.node_states.values())
        available = (policy.max_parallel_upgrades
                     if policy.max_parallel_upgrades > 0 else n_pending)
        if policy.max_unavailable is not None:
            available = min(available, scaled_value_from_int_or_percent(
                policy.max_unavailable, total, round_up=True))
        return max(1, available)

    def _cache_lookup_key(self, policy: "UpgradePolicySpec",
                          pending: list, in_progress: list, slots: int,
                          now: float) -> "Optional[tuple]":
        """Single-entry cache key: the forecast is pure in (fleet
        picture, policy knobs, traffic level), so steady reconcile
        passes — same pending/in-flight sets, same minute, unchanged
        utilization to 2dp — reuse it instead of re-cloning the fleet.
        Any change in the picture (a node admitted, traffic moved, the
        policy edited) misses and recomputes."""
        spec = self.spec
        hooks = getattr(policy, "policy_hooks", None)
        hooks_fp: tuple = ()
        if hooks is not None and getattr(hooks, "enable", False):
            hooks_fp = tuple(
                (h.hook, h.program) for h in (hooks.hooks or ()))
        util = None
        if self.trace is not None:
            util = round(float(self.trace.utilization(now)), 2)
        return (
            frozenset(ns.node.metadata.name for ns in pending),
            frozenset(name for _, ns in in_progress
                      for name in (ns.node.metadata.name,)),
            slots,
            spec.mode, spec.max_forecast_slo_risk_fraction,
            spec.max_forecast_makespan_seconds, spec.confidence,
            hooks_fp,
            util,
            int(now // 60),
            self.predictor.samples_total
            if self.predictor is not None else -1,
        )

    def _frozen_clone(self, state: "ClusterUpgradeState",
                      now: float) -> "object":
        """The read-only fleet snapshot every forecaster read goes
        through: a fresh FakeCluster loaded with CLONES of the
        snapshot's nodes, frozen before the first read — the tripwire
        that makes the read-only guarantee checkable rather than
        asserted."""
        from tpu_operator_libs.k8s.fake import FakeCluster
        from tpu_operator_libs.util import FakeClock

        clone = FakeCluster(clock=FakeClock(start=now))
        for node in state.all_nodes():
            clone.add_node(node.clone())
        clone.freeze(reason="preflight")
        return clone

    def _compute(self, clone: "object", state: "ClusterUpgradeState",
                 policy: "UpgradePolicySpec", pending: list,
                 in_progress: list, slots: int, now: float,
                 capacity: "Optional[CapacityBudgetController]") -> dict:
        import heapq

        spec = self.spec
        predictor = self.predictor
        # every per-node read below goes through the frozen clone's
        # read API (get_node returns a copy) — the tripwire proves the
        # whole forecast path is a pure function of the snapshot
        annotations_of = {}
        for name in [ns.node.metadata.name for ns in pending] \
                + [ns.node.metadata.name for _, ns in in_progress]:
            annotations_of[name] = dict(
                clone.get_node(name).metadata.annotations)

        # ---- maintenance window: conservative-bound deferrals -------
        window = policy.maintenance_window
        close = None
        if window is not None and getattr(window, "enable", False):
            resolve = getattr(window, "close_at", None)
            if resolve is not None:
                close = resolve(now)
        margin = float(getattr(window, "margin_seconds", 0) or 0) \
            if window is not None else 0.0
        deferred: list[str] = []
        eligible: list[str] = []
        for ns in pending:
            name = ns.node.metadata.name
            if close is not None and predictor is not None:
                bound = predictor.predict_node(
                    name, annotations_of[name], conservative=True)
                if now + bound + margin > close:
                    deferred.append(name)
                    continue
            eligible.append(name)

        # ---- LPT makespan (the predictive planner's _eta packing) ---
        loads: list[float] = []
        for state_label, ns in in_progress:
            name = ns.node.metadata.name
            if predictor is not None:
                loads.append(predictor.remaining_seconds(
                    name, state_label, annotations_of[name], now))
            else:
                loads.append(0.0)
        jobs = []
        for name in eligible:
            if predictor is not None:
                jobs.append(predictor.predict_node(
                    name, annotations_of[name]))
            else:
                jobs.append(0.0)
        jobs.sort(reverse=True)
        slot_count = max(1, len(loads) + max(0, slots))
        packed = loads + [0.0] * max(0, slot_count - len(loads))
        heapq.heapify(packed)
        for job in jobs:
            heapq.heappush(packed, heapq.heappop(packed) + job)
        makespan = max(packed) if (loads or jobs) else 0.0
        waves = []
        for i in range(0, len(jobs), slot_count):
            chunk = jobs[i:i + slot_count]
            waves.append({"nodes": len(chunk),
                          "predictedSeconds": round(chunk[0], 1)})

        # ---- confidence bounds from the retained error histogram ----
        error_ratio = (predictor.error_ratio(spec.confidence)
                       if predictor is not None else 0.0)
        error_samples = (predictor.error_samples
                         if predictor is not None else 0)
        lower = max(0.0, makespan * (1.0 - error_ratio))
        upper = makespan * (1.0 + error_ratio)

        # ---- policy hooks against a FRESH engine (zero pollution) ---
        forecast_holds = self._forecast_holds(
            clone, policy, eligible, state, slots, now, close)

        # ---- capacity/traffic replay over the forecast horizon ------
        slo = self._slo_replay(policy, capacity, eligible, slots,
                               max(upper, 1.0), now,
                               total_nodes=len(state.all_nodes()))

        # ---- verdict ------------------------------------------------
        breaches: list[str] = []
        if spec.max_forecast_makespan_seconds > 0 \
                and upper > spec.max_forecast_makespan_seconds:
            breaches.append("makespan")
        worst_fraction = slo["worstFraction"] if slo is not None else 0.0
        if worst_fraction > spec.max_forecast_slo_risk_fraction:
            breaches.append("slo-risk")
        if not breaches:
            verdict = VERDICT_ADMIT
        elif spec.mode == "required":
            verdict = VERDICT_REJECT
        else:
            verdict = VERDICT_ADVISORY

        forecast: dict = {
            "mode": spec.mode,
            "generatedAtSeconds": round(now, 1),
            "nodesPending": len(pending),
            "nodesInProgress": len(in_progress),
            "slots": slots,
            "makespan": {
                "expectedSeconds": round(makespan, 1),
                "lowerSeconds": round(lower, 1),
                "upperSeconds": round(upper, 1),
                "confidence": spec.confidence,
                "errorSamples": error_samples,
                "coldStart": error_samples == 0,
            },
            "waves": waves,
            "expected": {
                "holds": forecast_holds["count"],
                "windowDeferrals": len(deferred),
                "aborts": slo["aborts"] if slo is not None else 0,
                "pausedTicks": slo["pausedTicks"] if slo is not None
                else 0,
            },
            "thresholds": {
                "maxForecastSloRiskFraction":
                    spec.max_forecast_slo_risk_fraction,
                "maxForecastMakespanSeconds":
                    spec.max_forecast_makespan_seconds,
            },
            "breaches": breaches,
            "verdict": verdict,
        }
        if forecast_holds["rules"]:
            forecast["holdRules"] = forecast_holds["rules"]
        if slo is not None:
            forecast["sloRisk"] = {
                "worstClass": slo["worstClass"],
                "worstFraction": slo["worstFraction"],
                "classes": slo["classes"],
            }
        if close is not None:
            forecast["windowCloseSeconds"] = round(close, 1)
        return forecast

    def _forecast_holds(self, clone: "object",
                        policy: "UpgradePolicySpec",
                        eligible: "list[str]",
                        state: "ClusterUpgradeState", slots: int,
                        now: float, close: Optional[float]) -> dict:
        """Replay planner.admission / window.gate over the pending set
        on a THROWAWAY engine — the live engine's last_holds / audit
        stream never see forecast evaluations."""
        hooks = getattr(policy, "policy_hooks", None)
        if hooks is None or not getattr(hooks, "enable", False) \
                or not getattr(hooks, "hooks", None):
            return {"count": 0, "rules": {}}
        from tpu_operator_libs.policy.engine import PolicyEngine, node_env

        engine = PolicyEngine(self.keys)
        engine.refresh(hooks)
        registry = engine.registry
        check_admission = registry.has("planner.admission")
        check_window = registry.has("window.gate")
        if not check_admission and not check_window:
            return {"count": 0, "rules": {}}
        total = len(state.all_nodes())
        in_progress = sum(len(state.bucket(s))
                          for s in IN_PROGRESS_STATES)
        fleet_env = {"total": total, "inProgress": in_progress,
                     "unavailable": in_progress, "slots": slots,
                     "budget": slots}
        count = 0
        rules: dict[str, int] = {}
        for name in eligible:
            node = clone.get_node(name)
            env_node = node_env(node, state=str(
                node.metadata.labels.get(engine.state_label, "")))
            held = None
            if check_admission:
                verdict = registry.evaluate(
                    "planner.admission",
                    {"node": env_node, "fleet": fleet_env, "now": now},
                    subject=name)
                if verdict.value is not True:
                    held = verdict.rule or "policy-deny"
            if held is None and check_window:
                verdict = registry.evaluate(
                    "window.gate",
                    {"node": env_node, "now": now, "close": close},
                    subject=name)
                if verdict.value is not True:
                    held = verdict.rule or "policy-deny"
            if held is not None:
                count += 1
                rules[held] = rules.get(held, 0) + 1
        return {"count": count, "rules": dict(sorted(rules.items()))}

    def _slo_replay(self, policy: "UpgradePolicySpec",
                    capacity: "Optional[CapacityBudgetController]",
                    eligible: "list[str]", slots: int, horizon: float,
                    now: float, total_nodes: int) -> Optional[dict]:
        """Sweep the traffic picture across the forecast horizon.

        Demand comes from the diurnal trace when wired (soaks/benches/
        federation), else flat from the live controller's last status;
        serving capacity is reduced by the in-flight concurrency the
        rollout would hold out of service. Per-class risk maps each
        class to a contiguous segment of the rollout timeline in
        disruption-cost order (batch tiers drain first, interactive
        last — the cost ranker's admission order), using real per-class
        node shares when a classifier is wired and equal shares
        otherwise. Returns None when the policy is capacity-blind."""
        spec = policy.capacity
        if spec is None or not spec.enable:
            return None
        per_node = max(1, int(spec.per_node_capacity))
        status = capacity.last_status \
            if capacity is not None else None
        trace = self.trace
        if status:
            serving = int(status.get("servingNodes") or 0) or total_nodes
            flat_util = float(status.get("utilization") or 0.0)
        elif trace is not None:
            serving = total_nodes
            flat_util = float(trace.utilization(now))
        else:
            return None
        capacity_total = serving * per_node
        concurrency = min(slots, max(len(eligible), 1))
        avail = max(0, serving - concurrency) * per_node

        step = horizon / REPLAY_TICKS
        risks: list[float] = []
        paused_ticks = 0
        aborts = 0
        paused_prev = False
        for i in range(REPLAY_TICKS + 1):
            t = now + i * step
            util = (float(trace.utilization(t)) if trace is not None
                    else flat_util)
            demand = util * capacity_total
            risk = (max(0.0, demand - avail) / demand
                    if demand > 0 else 0.0)
            risks.append(risk)
            paused = util >= spec.peak_pause_utilization
            if paused:
                paused_ticks += 1
                if not paused_prev:
                    # a pause onset mid-rollout collapses the budget
                    # below what is already unavailable: every
                    # in-flight drain is forecast aborted
                    aborts += concurrency
            paused_prev = paused

        classes = list(spec.traffic_classes or ())
        if not classes:
            worst = max(risks)
            return {"worstClass": "fleet",
                    "worstFraction": round(worst, 4),
                    "classes": {"fleet": round(worst, 4)},
                    "aborts": aborts, "pausedTicks": paused_ticks}
        # disruption-cost order: batch tiers drain early in the
        # timeline, interactive last (mirrors DisruptionCostRanker)
        ordered = ([c for c in classes if not c.interactive]
                   + [c for c in classes if c.interactive])
        shares = self._class_shares(ordered, eligible)
        out: dict[str, float] = {}
        worst_class, worst_fraction = "", 0.0
        cursor = 0.0
        n_ticks = len(risks)
        for cls in ordered:
            begin = int(cursor * n_ticks)
            cursor = min(1.0, cursor + shares[cls.name])
            end = max(begin + 1, int(cursor * n_ticks))
            segment = risks[begin:min(end, n_ticks)] or [risks[-1]]
            fraction = round(max(segment), 4)
            out[cls.name] = fraction
            if fraction >= worst_fraction:
                worst_class, worst_fraction = cls.name, fraction
        return {"worstClass": worst_class,
                "worstFraction": worst_fraction,
                "classes": dict(sorted(out.items())),
                "aborts": aborts, "pausedTicks": paused_ticks}

    def _class_shares(self, ordered: list,
                      eligible: "list[str]") -> "dict[str, float]":
        if self.classify is not None and eligible:
            counts = {cls.name: 0 for cls in ordered}
            matched = 0
            for name in eligible:
                cls = self.classify(name)
                if cls in counts:
                    counts[cls] += 1
                    matched += 1
            if matched:
                return {name: count / matched
                        for name, count in counts.items()}
        share = 1.0 / len(ordered)
        return {cls.name: share for cls in ordered}
