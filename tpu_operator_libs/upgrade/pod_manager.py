"""PodManager: eviction, restarts, completion-waits and the revision oracle.

Equivalent of the reference PodManager (pod_manager.go). Four jobs:

a. ``schedule_pod_eviction`` — delete workload pods selected by the injected
   deletion filter, one async worker per node, deduplicated by an in-flight
   set (pod_manager.go:125-232).
b. ``schedule_pods_restart`` — delete runtime pods so the DaemonSet
   recreates them at the new revision (pod_manager.go:236-254).
c. ``schedule_check_on_pod_completion`` — wait for workload pods to finish,
   with the timeout checkpointed in a node annotation so it survives
   reconciles (pod_manager.go:259-320, 333-371).
d. revision-hash getters — the "does this node need an upgrade" oracle:
   compare the pod's ``controller-revision-hash`` label with the DaemonSet's
   newest ControllerRevision (pod_manager.go:83-121).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Optional

from tpu_operator_libs.api.upgrade_policy import (
    PodDeletionSpec,
    WaitForCompletionSpec,
)
from tpu_operator_libs.consts import (
    POD_CONTROLLER_REVISION_HASH_LABEL,
    UpgradeState,
)
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    K8sClient,
)
from tpu_operator_libs.k8s.drain import DrainHelper, PodDeleteStatus
from tpu_operator_libs.k8s.objects import DaemonSet, Node, Pod, PodPhase
from tpu_operator_libs.k8s.selectors import selector_from_labels
from tpu_operator_libs.upgrade.state_provider import NodeUpgradeStateProvider
from tpu_operator_libs.util import (
    Clock,
    Event,
    EventRecorder,
    NameSet,
    Worker,
    log_event,
)

if TYPE_CHECKING:
    from tpu_operator_libs.upgrade.nudger import ReconcileNudger

logger = logging.getLogger(__name__)

#: Backoff for transient-error eviction retries (seconds); see the
#: drain manager's jitter-free rationale — retries land on the nudger's
#: coalescing timer wheel, and determinism keeps seeded replays exact.
EVICTION_RETRY_SECONDS = 5.0

#: Decides whether a workload pod must be deleted before the runtime upgrade
#: (reference PodDeletionFilter, pod_manager.go:76).
PodDeletionFilter = Callable[[Pod], bool]

#: Eviction-time veto: called with (node, pods_to_delete) right before
#: eviction; returning False leaves the node parked in
#: pod-deletion-required for the next reconcile. Unlike the deletion
#: *filter* (which silently skips pods), a closed gate blocks progress —
#: the hook the Orbax checkpoint-durability gate plugs into
#: (tpu_operator_libs.health.checkpoint_gate; BASELINE config #4).
#: Shared semantics live in tpu_operator_libs.upgrade.gate.GateKeeper.
from tpu_operator_libs.upgrade.gate import EvictionGate, GateKeeper  # noqa: E402,F401


@dataclass
class PodManagerConfig:
    """Selector/config bundle for pod jobs (pod_manager.go:63-68)."""

    nodes: list[Node] = field(default_factory=list)
    deletion_spec: Optional[PodDeletionSpec] = None
    wait_for_completion_spec: Optional[WaitForCompletionSpec] = None
    drain_enabled: bool = False


class RevisionHashError(RuntimeError):
    """Revision hash could not be determined."""


class PodManager:
    def __init__(self, client: K8sClient,
                 provider: NodeUpgradeStateProvider,
                 deletion_filter: Optional[PodDeletionFilter] = None,
                 recorder: Optional[EventRecorder] = None,
                 clock: Optional[Clock] = None,
                 worker: Optional[Worker] = None,
                 eviction_gate: Optional[EvictionGate] = None,
                 nudger: Optional["ReconcileNudger"] = None) -> None:
        self._client = client
        self._provider = provider
        self._deletion_filter = deletion_filter
        self._gatekeeper = GateKeeper(provider.keys, recorder,
                                      "pod deletion")
        self._gatekeeper.set_gate(eviction_gate)
        self._recorder = recorder
        self._clock = clock or Clock()
        self._worker = worker or Worker()
        self._nodes_in_progress = NameSet()
        self.nudger = nudger
        self._keys = provider.keys
        # Per-snapshot revision-oracle memo (see
        # get_daemon_set_revision_hash); reset by the state manager at
        # every build_state. Locked: bucket workers consult it in
        # parallel.
        self._revision_memo_lock = threading.Lock()
        self._revision_memo: dict[str, str] = {}

    @property
    def deletion_filter(self) -> Optional[PodDeletionFilter]:
        return self._deletion_filter

    @property
    def eviction_gate(self) -> Optional[EvictionGate]:
        return self._gatekeeper.gate

    def set_eviction_gate(self, gate: Optional[EvictionGate]) -> None:
        self._gatekeeper.set_gate(gate)

    def abandon_stale_gate_deferrals(self, still_wanted: "set[str]") -> None:
        """Hand gate-parked nodes that left every eviction-wanting state
        back to the gate's ``release`` hook (GateKeeper.abandon_stale)."""
        self._gatekeeper.abandon_stale(still_wanted)

    def release_gate(self, node: Node, pods: "list[Pod]") -> None:
        """Mid-flight abort: return one node's endpoints to admitting
        (GateKeeper.release_node — durable-label driven, so it works
        across operator crash-restarts)."""
        self._gatekeeper.release_node(node, pods)

    # ------------------------------------------------------------------
    # (d) revision oracle
    # ------------------------------------------------------------------
    def get_pod_revision_hash(self, pod: Pod) -> str:
        """Pod's controller-revision-hash label (pod_manager.go:87-92)."""
        try:
            return pod.metadata.labels[POD_CONTROLLER_REVISION_HASH_LABEL]
        except KeyError:
            raise RevisionHashError(
                f"controller-revision-hash label not present for pod "
                f"{pod.name}") from None

    def reset_revision_cache(self) -> None:
        """Drop the per-snapshot revision memo (called by the state
        manager at the start of every build_state)."""
        with self._revision_memo_lock:
            self._revision_memo.clear()

    def get_daemon_set_revision_hash(self, ds: DaemonSet) -> str:
        """Newest ControllerRevision hash for the DaemonSet
        (pod_manager.go:95-121).

        The reference selects revisions by "name starts with the DS name",
        which collides between DaemonSets sharing a name prefix
        (pod_manager.go:106). We additionally require the suffix after
        ``<name>-`` to be a single hash segment (no further dashes), which
        holds for controller-generated revision names.

        Memoized per snapshot (keyed by DS UID, reset each build_state):
        the in-sync oracle runs once per NODE per pass, and without the
        memo a 1024-node steady-state pass issued 1024 identical
        ControllerRevision LISTs — the dominant per-pass API fan-out at
        fleet scale. Within one snapshot the newest revision is
        immutable by construction, so the memo cannot change any
        decision.
        """
        with self._revision_memo_lock:
            memoized = self._revision_memo.get(ds.metadata.uid)
        if memoized is not None:
            return memoized
        selector = selector_from_labels(ds.spec.selector)
        revisions = self._client.list_controller_revisions(
            ds.metadata.namespace, selector)
        prefix = f"{ds.metadata.name}-"
        owned = [r for r in revisions
                 if r.metadata.name.startswith(prefix)
                 and "-" not in r.metadata.name[len(prefix):]]
        if not owned:
            raise RevisionHashError(
                f"no revision found for daemonset {ds.metadata.name}")
        newest = max(owned, key=lambda r: r.revision)
        result = newest.metadata.name[len(prefix):]
        with self._revision_memo_lock:
            self._revision_memo[ds.metadata.uid] = result
        return result

    def get_previous_daemon_set_revision_hash(
            self, ds: DaemonSet) -> Optional[str]:
        """Hash of the DaemonSet's SECOND-newest ControllerRevision — the
        rollback target after a canary halt — or None when the DS has no
        history to fall back to (first-ever revision). Same ownership
        filter as the newest-hash oracle; not memoized: it runs once per
        halt, not once per node per pass."""
        selector = selector_from_labels(ds.spec.selector)
        revisions = self._client.list_controller_revisions(
            ds.metadata.namespace, selector)
        prefix = f"{ds.metadata.name}-"
        owned = [r for r in revisions
                 if r.metadata.name.startswith(prefix)
                 and "-" not in r.metadata.name[len(prefix):]]
        if len(owned) < 2:
            return None
        ordered = sorted(owned, key=lambda r: r.revision)
        return ordered[-2].metadata.name[len(prefix):]

    # ------------------------------------------------------------------
    # (a) pod eviction
    # ------------------------------------------------------------------
    def schedule_pod_eviction(self, config: PodManagerConfig) -> None:
        """Delete filter-selected pods on each node, async per node
        (pod_manager.go:125-232). On success the node moves to
        pod-restart-required; on failure to drain-required when drain is
        enabled, else upgrade-failed (pod_manager.go:396-406)."""
        if not config.nodes:
            logger.info("no nodes scheduled for pod deletion")
            return
        spec = config.deletion_spec
        if spec is None:
            raise ValueError("pod deletion spec should not be empty")
        if self._deletion_filter is None:
            raise ValueError("pod deletion filter not configured")

        def additional_filter(pod: Pod) -> PodDeleteStatus:
            if self._deletion_filter(pod):
                return PodDeleteStatus.okay()
            return PodDeleteStatus.skip("not selected by deletion filter")

        helper = DrainHelper(
            client=self._client,
            force=spec.force,
            ignore_all_daemon_sets=True,
            delete_empty_dir_data=spec.delete_empty_dir,
            timeout_seconds=spec.timeout_seconds,
            additional_filters=[additional_filter],
            clock=self._clock,
        )

        # ONE all-namespaces LIST grouped by spec.nodeName instead of a
        # pods-on-node LIST per target node: a fleet-scale eviction wave
        # previously paid O(wave) apiserver LIST round-trips before the
        # first pod was touched. Error semantics match the old per-node
        # list exactly, applied wave-wide: a transient failure parks
        # every node for the next reconcile; a non-transient one takes
        # the reference's drain-or-failed escalation
        # (pod_manager.go:396-406) for each node.
        try:
            pods_by_node = self._pods_by_node(self._client.list_pods(
                namespace=None))
        except (ApiServerError, ConflictError) as exc:
            logger.warning("transient error listing pods for eviction "
                           "wave; deferring %d node(s): %s",
                           len(config.nodes), exc)
            return
        except Exception as exc:  # noqa: BLE001 — reference escalation path
            logger.error("failed to list pods for eviction wave: %s", exc)
            for node in config.nodes:
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to delete workload pods on the node for "
                          f"the runtime upgrade: {exc}")
                self._update_node_to_drain_or_failed(
                    node, config.drain_enabled)
            return
        for node in config.nodes:
            if not self._nodes_in_progress.add(node.metadata.name):
                logger.info("node %s already getting pods deleted, skipping",
                            node.metadata.name)
                continue
            node_pods = pods_by_node.get(node.metadata.name, [])
            self._worker.submit(
                lambda n=node, p=node_pods: self._evict_node_pods(
                    n, helper, config, p))

    @staticmethod
    def _pods_by_node(pods: list[Pod]) -> dict[str, list[Pod]]:
        grouped: dict[str, list[Pod]] = {}
        for pod in pods:
            if pod.spec.node_name:
                grouped.setdefault(pod.spec.node_name, []).append(pod)
        return grouped

    def _evict_node_pods(self, node: Node, helper: DrainHelper,
                         config: PodManagerConfig,
                         pods: list[Pod]) -> None:
        name = node.metadata.name
        try:
            to_delete = [p for p in pods if self._deletion_filter(p)]
            if not to_delete:
                logger.info("no pods require deletion on node %s", name)
                self._change_state_quietly(
                    node, UpgradeState.POD_RESTART_REQUIRED)
                return

            # Gate check comes FIRST: while the workload's checkpoint is
            # not durable the node must park in pod-deletion-required — no
            # path below (including the drain fallback) may run.
            if not self._gatekeeper.allows(node, to_delete):
                return

            deletable, errors = helper.get_pods_for_deletion(name)
            if len(deletable) != len(to_delete):
                logger.error("cannot delete all required pods on %s: %s",
                             name, errors)
                self._update_node_to_drain_or_failed(
                    node, config.drain_enabled)
                return

            helper.delete_or_evict_pods(deletable)
            logger.info("deleted pods on node %s", name)
            self._change_state_quietly(
                node, UpgradeState.POD_RESTART_REQUIRED)
            log_event(self._recorder, node, Event.NORMAL,
                      self._keys.event_reason,
                      "Deleted workload pods on the node for the runtime "
                      "upgrade")
        except (ApiServerError, ConflictError) as exc:
            # Transient apiserver failure: escalating to drain-or-failed
            # could strand the node in upgrade-failed (out-of-sync pod ⇒
            # auto-recovery can never fire). Park in
            # pod-deletion-required; a backoff wakeup retries without
            # waiting out the resync interval.
            logger.warning("transient error deleting pods on node %s; "
                           "deferring: %s", name, exc)
            if self.nudger is not None:
                self.nudger.nudge_after(EVICTION_RETRY_SECONDS,
                                        "eviction-retry")
        except Exception as exc:  # noqa: BLE001 — worker boundary
            logger.error("failed to delete pods on node %s: %s", name, exc)
            log_event(self._recorder, node, Event.WARNING,
                      self._keys.event_reason,
                      f"Failed to delete workload pods on the node for the "
                      f"runtime upgrade: {exc}")
            self._update_node_to_drain_or_failed(node, config.drain_enabled)
        finally:
            self._nodes_in_progress.remove(name)

    def _update_node_to_drain_or_failed(self, node: Node,
                                        drain_enabled: bool) -> None:
        next_state = UpgradeState.FAILED
        if drain_enabled:
            logger.info("pod deletion failed on %s; drain enabled, will "
                        "attempt node drain", node.metadata.name)
            log_event(self._recorder, node, Event.WARNING,
                      self._keys.event_reason,
                      "Pod deletion failed but drain is enabled in spec. "
                      "Will attempt a node drain")
            next_state = UpgradeState.DRAIN_REQUIRED
        self._change_state_quietly(node, next_state)

    def _change_state_quietly(self, node: Node, state: UpgradeState) -> None:
        """State write from an async worker: errors are logged, not raised —
        the next reconcile re-derives the correct action (the reference
        ignores these errors outright, pod_manager.go:189,223). A
        committed outcome wakes the reconcile loop immediately instead
        of waiting for the next poll."""
        try:
            self._provider.change_node_upgrade_state(node, state)
        except Exception as exc:  # noqa: BLE001 — worker boundary
            logger.error("failed to change state of node %s to %s: %s",
                         node.metadata.name, state, exc)
            return
        if self.nudger is not None:
            self.nudger.nudge("eviction")

    # ------------------------------------------------------------------
    # (b) restart runtime pods
    # ------------------------------------------------------------------
    def schedule_pods_restart(self, pods: list[Pod]) -> int:
        """Delete runtime pods so the DaemonSet controller recreates them
        with the new template (pod_manager.go:236-254). Synchronous.

        A TRANSIENT cluster error (5xx / conflict) on one pod's delete
        defers only that pod — its node re-enters pod-restart-required
        on the next reconcile — and the remaining pods still restart
        (the same per-node isolation the state manager's processors
        apply; under a sustained apiserver error rate an abort here
        skipped every later pod AND every later state bucket). Hard
        errors still abort the pass. Returns the number of deferred
        pods so callers can requeue promptly."""
        if not pods:
            logger.info("no pods scheduled to restart")
            return 0
        from tpu_operator_libs.k8s.client import (
            ApiServerError,
            ConflictError,
            NotFoundError,
        )

        deferred = 0
        for pod in pods:
            logger.info("deleting pod %s", pod.name)
            try:
                self._client.delete_pod(pod.namespace, pod.name)
            except NotFoundError:
                # Already gone (e.g. a concurrent reconcile won the race):
                # the restart goal is achieved — idempotent by design.
                logger.info("pod %s already deleted", pod.name)
            except (ApiServerError, ConflictError) as exc:
                logger.warning("transient error deleting pod %s; "
                               "deferring to the next reconcile: %s",
                               pod.name, exc)
                deferred += 1
            except Exception as exc:
                log_event(self._recorder, pod, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to restart runtime pod: {exc}")
                raise
        return deferred

    # ------------------------------------------------------------------
    # (c) wait for workload completion
    # ------------------------------------------------------------------
    def schedule_check_on_pod_completion(self,
                                         config: PodManagerConfig) -> None:
        """Per node: if no selected workload pod is still running/pending,
        advance to pod-deletion-required; otherwise keep waiting, enforcing
        the policy timeout via a start-time annotation
        (pod_manager.go:259-320).

        The reference spawns one goroutine per node but joins them all
        before returning (wg.Wait, pod_manager.go:318); sequential execution
        is observably identical and deterministic.
        """
        spec = config.wait_for_completion_spec
        assert spec is not None
        # ONE selector LIST grouped by node instead of a LIST per
        # waiting node (the same O(wave)→O(1) wire-cost fix as the
        # eviction path). A transient failure leaves every node parked
        # in wait-for-jobs for the next reconcile.
        try:
            pods_by_node = self._pods_by_node(self._client.list_pods(
                namespace=None, label_selector=spec.pod_selector))
        except (ApiServerError, ConflictError) as exc:
            logger.warning("transient error listing workload pods for "
                           "completion checks; deferring %d node(s): %s",
                           len(config.nodes), exc)
            return
        for node in config.nodes:
            pods = pods_by_node.get(node.metadata.name, [])
            running = any(self.is_pod_running_or_pending(p) for p in pods)
            if running:
                logger.info("workload pods still running on node %s",
                            node.metadata.name)
                if spec.timeout_seconds != 0:
                    try:
                        self.handle_timeout_on_pod_completions(
                            node, spec.timeout_seconds)
                    except Exception as exc:  # noqa: BLE001
                        log_event(self._recorder, node, Event.WARNING,
                                  self._keys.event_reason,
                                  f"Failed to handle timeout for job "
                                  f"completions: {exc}")
                continue
            annotation = self._keys.pod_completion_start_annotation
            try:
                # timer-stamp removal rides the transition's merge
                # patch: one write, crash-atomic
                self._provider.change_node_upgrade_state(
                    node, UpgradeState.POD_DELETION_REQUIRED,
                    annotations={annotation: None})
            except Exception as exc:  # noqa: BLE001 — worker boundary
                logger.error("failed to advance node %s past job "
                             "completion: %s", node.metadata.name, exc)
                log_event(self._recorder, node, Event.WARNING,
                          self._keys.event_reason,
                          f"Failed to advance node after job "
                          f"completions: {exc}")

    def handle_timeout_on_pod_completions(self, node: Node,
                                          timeout_seconds: int) -> None:
        """Start or check the wait-for-jobs timer (pod_manager.go:333-371):
        first sighting stamps the start-time annotation; once expired the
        node is forced to pod-deletion-required and the stamp removed."""
        annotation = self._keys.pod_completion_start_annotation
        now = int(self._clock.now())
        stamp = node.metadata.annotations.get(annotation)
        if stamp is None:
            self._provider.change_node_upgrade_annotation(
                node, annotation, str(now))
            if self.nudger is not None:
                # precise wakeup at expiry (slot-coalesced with the
                # rest of the wave); re-registered below on later
                # sightings so it survives operator restarts
                self.nudger.nudge_at(now + timeout_seconds,
                                     "wait-for-jobs-timeout")
            return
        start = int(stamp)
        if self.nudger is not None and now <= start + timeout_seconds:
            self.nudger.nudge_at(start + timeout_seconds,
                                 "wait-for-jobs-timeout")
        if now > start + timeout_seconds:
            # forced advance + stamp removal as ONE merge patch (the
            # split form could crash between the two writes and leave a
            # stale stamp for the next wait to misread)
            try:
                self._provider.change_node_upgrade_state(
                    node, UpgradeState.POD_DELETION_REQUIRED,
                    annotations={annotation: None})
            except Exception as exc:  # noqa: BLE001 — worker boundary
                logger.error("failed to change state of node %s to %s: %s",
                             node.metadata.name,
                             UpgradeState.POD_DELETION_REQUIRED, exc)
                return
            logger.info("timeout exceeded for job completions on node %s",
                        node.metadata.name)

    @staticmethod
    def is_pod_running_or_pending(pod: Pod) -> bool:
        """Running/Pending block progress; Succeeded/Failed do not
        (pod_manager.go:374-394)."""
        return pod.status.phase in (PodPhase.RUNNING, PodPhase.PENDING)

    def join(self, timeout: float = 30.0) -> None:
        """Wait for in-flight async eviction workers (test/sim helper)."""
        self._worker.join(timeout)
