"""Shared eviction-gate evaluation for the pod-deletion and drain paths.

One implementation of the safety-critical semantics both managers need
(pod_manager / drain_manager): a closed gate parks the node, a RAISING gate
counts as closed (delay, never escalate — escalation would bypass the
checkpoint-durability guarantee), and the deferral event is emitted once
per parked node, not on every reconcile pass.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from tpu_operator_libs.consts import UpgradeKeys
from tpu_operator_libs.k8s.objects import Node, Pod
from tpu_operator_libs.util import Event, EventRecorder, NameSet, log_event

logger = logging.getLogger(__name__)

#: (node, pods about to be evicted) -> True when eviction may proceed.
EvictionGate = Callable[[Node, list[Pod]], bool]


class GateKeeper:
    """Evaluates an optional EvictionGate with park-don't-escalate
    semantics and one-shot deferral events."""

    def __init__(self, keys: UpgradeKeys,
                 recorder: Optional[EventRecorder],
                 action: str) -> None:
        self._gate: Optional[EvictionGate] = None
        self._keys = keys
        self._recorder = recorder
        self._action = action  # "pod deletion" | "drain" — event wording
        self._deferred = NameSet()
        # Last (node, pods) snapshot per parked node, so an abandon can
        # replay them into the gate's release() even after the node
        # left the eviction-wanting bucket (or vanished entirely).
        # Guarded by _parked_lock: allows() runs on async drain/
        # pod-deletion worker threads while abandon_stale() runs on the
        # reconcile thread.
        import threading

        self._parked: dict[str, tuple[Node, list[Pod]]] = {}
        self._parked_lock = threading.Lock()

    @property
    def gate(self) -> Optional[EvictionGate]:
        return self._gate

    def set_gate(self, gate: Optional[EvictionGate]) -> None:
        """Install (or clear) the gate. Nodes still parked against the
        OUTGOING gate are handed back to ITS release hook first —
        replacing a stateful gate (or disabling gating) must not strand
        endpoints the old gate flipped to draining, because
        abandon_stale can only consult the current gate."""
        if gate is not self._gate and self._gate is not None:
            self._release_all(self._gate)
        self._gate = gate

    def _release_all(self, gate: EvictionGate) -> None:
        with self._parked_lock:
            parked = list(self._parked.items())
            self._parked.clear()
        release = getattr(gate, "release", None)
        for name, (node, pods) in parked:
            self._deferred.remove(name)
            if release is None:
                continue
            logger.info("gate replaced; releasing %s deferral for "
                        "node %s", self._action, name)
            try:
                release(node, pods)
            except Exception as exc:  # noqa: BLE001 — gate boundary
                logger.warning("gate release raised for node %s: %s",
                               name, exc)

    def allows(self, node: Node, pods: list[Pod]) -> bool:
        """True when the gate is absent or open. On False the caller must
        leave the node in its current state for the next reconcile."""
        if self._gate is None:
            return True
        name = node.metadata.name
        try:
            open_ = bool(self._gate(node, pods))
        except Exception as exc:  # noqa: BLE001 — gate boundary
            logger.warning("eviction gate raised for node %s (treating as "
                           "closed): %s", name, exc)
            open_ = False
        if open_:
            self._deferred.remove(name)
            with self._parked_lock:
                self._parked.pop(name, None)
            return True
        logger.info("eviction gate closed for node %s; deferring %s",
                    name, self._action)
        with self._parked_lock:
            self._parked[name] = (node, list(pods))
        if self._deferred.add(name):
            log_event(self._recorder, node, Event.NORMAL,
                      self._keys.event_reason,
                      f"{self._action.capitalize()} deferred: "
                      f"checkpoint/eviction gate not yet open")
        return False

    def release_node(self, node: Node, pods: list[Pod]) -> None:
        """Explicitly hand ONE node back to the gate's ``release`` hook
        (the mid-flight abort path, state_manager.
        process_abort_required_nodes).

        Unlike :meth:`abandon_stale` this does not depend on the
        in-memory parked record: an operator that crashed mid-abort
        rebuilds with an empty GateKeeper, yet the resumed abort must
        still return the node's serving endpoints to admitting — so the
        release is driven from the durable abort-required label, with
        the caller supplying the node's current pods for the gate's
        resolver. Idempotent (ServingDrainGate.release just resumes).
        """
        name = node.metadata.name
        with self._parked_lock:
            self._parked.pop(name, None)
        self._deferred.remove(name)
        release = getattr(self._gate, "release", None)
        if release is None:
            return
        try:
            release(node, pods)
        except Exception as exc:  # noqa: BLE001 — gate boundary
            logger.warning("gate release raised for node %s: %s",
                           name, exc)

    def abandon_stale(self, still_wanted: "set[str]") -> None:
        """Release parked nodes the upgrade flow no longer wants evicted.

        Evaluating a stateful gate (e.g. ServingDrainGate) has side
        effects — it flips endpoints to draining. If the flow then stops
        wanting the node's pods gone (policy change, auto-upgrade
        disabled, node vanished), nothing would ever re-open those
        endpoints. The state manager calls this at the end of each pass
        with the names still in an eviction-wanting state; any other
        parked node is handed back to the gate's optional ``release``
        hook and its one-shot deferral marker cleared.
        """
        with self._parked_lock:
            stale = [n for n in self._parked if n not in still_wanted]
        for name in stale:
            with self._parked_lock:
                parked = self._parked.pop(name, None)
            if parked is None:
                # an async gate evaluation opened (and un-parked) the
                # node between the snapshot and now — nothing to release
                continue
            node, pods = parked
            self._deferred.remove(name)
            release = getattr(self._gate, "release", None)
            if release is None:
                continue
            logger.info("eviction no longer wanted for node %s; "
                        "releasing %s gate", name, self._action)
            try:
                release(node, pods)
            except Exception as exc:  # noqa: BLE001 — gate boundary
                logger.warning("gate release raised for node %s: %s",
                               name, exc)
