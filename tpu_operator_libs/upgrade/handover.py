"""Traffic-class-aware drain ordering + prewarmed session handover.

PR 10's capacity budget made the disruption budget breathe with live
serving load, but its signal is fleet-level: the budget knows HOW MANY
nodes may drain, not WHICH — a trough-time wave can condemn the only
replica of a hot interactive model while idle batch nodes sit
untouched. This module closes that gap with two cooperating pieces:

- :class:`DisruptionCostRanker` — a planner layer (outermost, the PR 9
  ``PredictiveWavePlanner`` idiom: a persistent wrapper that reorders
  and filters candidates while every budget/slice/canary admission
  decision stays with the inner chain). Each pass it rebuilds the live
  serving picture from the same endpoint source the
  ``CapacityBudgetController`` reads and ranks drain candidates by
  disruption cost: non-serving nodes first, then batch-only nodes,
  then interactive nodes whose models stay replicated, then
  sole-replica batch nodes — and it HOLDS a node whose drain would
  leave an interactive model below its class's ``minReplicas``
  admitting replicas, with an audited reason
  (``sole-replica-interactive`` / ``awaiting-prewarm``).
- :class:`PrewarmCoordinator` — the PR 6 reserve→join idiom at serving
  granularity. Before a held incumbent may drain, an already-upgraded
  spare (upgrade-done, ready, schedulable — typically a just-finished
  node of the same wave) is RESERVED with a durable node annotation,
  the deployment's readiness hook brings a replacement replica up on
  it, and a second durable stamp records readiness. Both stamps ride
  the crash-fused provider write path, reserve strictly before ready,
  so a mid-prewarm operator crash resumes (or releases) the prewarm
  from cluster state alone — and both are deleted on ONE merge patch
  when the incumbent finishes, leaving zero residue.

The hold lifts through the LIVE picture: once the replacement replica
is admitting, the incumbent is no longer its model's sole replica and
ranks like any other interactive node. Router-side session handover
(the serving deployment's half; ``chaos/serving.ServingFleetSim`` is
the reference implementation) then re-binds the incumbent's sessions
to the replacement behind the class drain deadline, so the drain
quiesces without dropping a single generation.

Fail-open contract: with no endpoint source, an empty source, or no
declared traffic classes the ranker is never installed (or degrades to
a single pass-through ``inner.plan`` call) — class-blind fleets keep
PR 10 behavior bit for bit.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Callable, Optional

from tpu_operator_libs.consts import IN_PROGRESS_STATES, UpgradeState
from tpu_operator_libs.util import Clock

if TYPE_CHECKING:  # pragma: no cover - types only
    from tpu_operator_libs.api.upgrade_policy import TrafficClassSpec
    from tpu_operator_libs.consts import UpgradeKeys
    from tpu_operator_libs.upgrade.state_manager import (
        ClusterUpgradeState,
        NodeUpgradeState,
        UpgradePlanner,
    )
    from tpu_operator_libs.upgrade.state_provider import (
        NodeUpgradeStateProvider,
    )

logger = logging.getLogger(__name__)

#: (spare, incumbent, model, traffic_class) -> replacement replica is
#: up AND passing readiness. The deployment seam: called once per pass
#: per reservation; the first call is also the "start the replica"
#: signal (idempotent on the serving side).
ReadinessHook = Callable[[str, str, str, str], bool]

#: (spare, incumbent) -> the serving side may retire the replacement
#: replica (gracefully — drain it, never kill it).
ReleaseHook = Callable[[str, str], None]

#: (kind, node, decision, rule, inputs) recorder — the manager wires
#: this into its DecisionAudit so every hold/prewarm decision explains
#: itself.
AuditHook = Callable[[str, str, str, str, dict], None]

#: Hold rules the ranker emits (also the explain-chain vocabulary).
HOLD_SOLE_REPLICA = "sole-replica-interactive"
HOLD_AWAITING_PREWARM = "awaiting-prewarm"
#: Rank rule: the node carries the remediation machine's at-risk stamp
#: (condemn-before-fail), so its drain is already planned — it ranks as
#: the cheapest possible disruption candidate.
RANK_AT_RISK = "at-risk-precursor"


class _Reservation:
    """One durable prewarm reservation, rehydrated from node
    annotations each pass (the coordinator holds no in-memory truth)."""

    __slots__ = ("spare", "incumbent", "model", "traffic_class",
                 "ready", "spare_node")

    def __init__(self, spare: str, incumbent: str, model: str,
                 traffic_class: str, ready: bool,
                 spare_node: "object") -> None:
        self.spare = spare
        self.incumbent = incumbent
        self.model = model
        self.traffic_class = traffic_class
        self.ready = ready
        self.spare_node = spare_node


class PrewarmCoordinator:
    """Crash-ordered reserve→ready→release of prewarm spares.

    Stateless-durable: every pass re-derives its reservations from
    node annotations alone, so an operator crash (or shard takeover)
    at ANY point mid-prewarm resumes without residue — the worst case
    is one repeated readiness probe.
    """

    def __init__(self, provider: "NodeUpgradeStateProvider",
                 keys: "UpgradeKeys",
                 clock: Optional[Clock] = None,
                 readiness: Optional[ReadinessHook] = None,
                 release: Optional[ReleaseHook] = None,
                 audit: Optional[AuditHook] = None) -> None:
        self.provider = provider
        self.keys = keys
        self._clock = clock or Clock()
        self.readiness = readiness
        self.release = release
        self.audit = audit
        #: lifetime counters (metrics.observe_capacity feed)
        self.reservations_total = 0
        self.ready_total = 0
        self.released_total = 0

    # ------------------------------------------------------------------
    # durable-state scan
    # ------------------------------------------------------------------
    def reservations(self, state: "ClusterUpgradeState",
                     ) -> "dict[str, _Reservation]":
        """incumbent -> reservation, from the snapshot's annotations."""
        out: dict[str, _Reservation] = {}
        reserve_key = self.keys.prewarm_reservation_annotation
        ready_key = self.keys.prewarm_ready_annotation
        for node in state.all_nodes():
            value = node.metadata.annotations.get(reserve_key)
            if not value:
                continue
            incumbent, _, rest = value.partition(":")
            model, _, traffic_class = rest.partition(":")
            ready_stamp = node.metadata.annotations.get(ready_key, "")
            out[incumbent] = _Reservation(
                spare=node.metadata.name, incumbent=incumbent,
                model=model, traffic_class=traffic_class,
                ready=ready_stamp.startswith(f"{incumbent}:"),
                spare_node=node)
        return out

    def _audit(self, node: str, decision: str, rule: str,
               inputs: dict) -> None:
        if self.audit is not None:
            self.audit("prewarm", node, decision, rule, inputs)

    # ------------------------------------------------------------------
    # the per-hold drive
    # ------------------------------------------------------------------
    def ensure(self, incumbent: str, model: str, traffic_class: str,
               state: "ClusterUpgradeState") -> str:
        """Drive one incumbent's prewarm a step; returns the arc state
        (``reserved`` / ``warming`` / ``ready`` / ``unavailable``).

        Idempotent per pass: an existing healthy reservation is only
        probed for readiness; a dead spare's reservation is released
        and a fresh spare reserved (the transient-node-kill path)."""
        live = self.reservations(state)
        reservation = live.get(incumbent)
        if reservation is not None:
            node = reservation.spare_node
            if not node.is_ready():
                # the spare died mid-prewarm: abandon its stamps (one
                # patch) and fall through to reserve a replacement
                self._release_one(reservation, rule="spare-lost")
            else:
                return self._probe(reservation)
        spare = self._pick_spare(incumbent, state,
                                 reserved={r.spare
                                           for r in live.values()})
        if spare is None:
            return "unavailable"
        value = f"{incumbent}:{model}:{traffic_class}"
        self.provider.change_node_upgrade_annotations(
            spare, {self.keys.prewarm_reservation_annotation: value})
        self.reservations_total += 1
        self._audit(spare.metadata.name, "reserve", "prewarm-reserve",
                    {"incumbent": incumbent, "model": model,
                     "class": traffic_class})
        logger.info(
            "prewarm: reserved spare %s for incumbent %s "
            "(model %s, class %s)", spare.metadata.name, incumbent,
            model, traffic_class)
        # first readiness probe doubles as the start-the-replica signal
        self._probe(_Reservation(
            spare=spare.metadata.name, incumbent=incumbent,
            model=model, traffic_class=traffic_class, ready=False,
            spare_node=spare))
        return "reserved"

    def _probe(self, reservation: _Reservation) -> str:
        if reservation.ready:
            return "ready"
        if self.readiness is None:
            return "warming"
        try:
            ready = bool(self.readiness(
                reservation.spare, reservation.incumbent,
                reservation.model, reservation.traffic_class))
        except Exception as exc:  # noqa: BLE001 — deployment seam: a
            # broken hook must park the prewarm, never wedge the pass
            logger.warning("prewarm readiness hook raised for spare "
                           "%s: %s", reservation.spare, exc)
            return "warming"
        if not ready:
            return "warming"
        stamp = f"{reservation.incumbent}:{self._clock.now():g}"
        self.provider.change_node_upgrade_annotations(
            reservation.spare_node,
            {self.keys.prewarm_ready_annotation: stamp})
        self.ready_total += 1
        self._audit(reservation.spare, "ready", "prewarm-ready",
                    {"incumbent": reservation.incumbent,
                     "model": reservation.model})
        logger.info("prewarm: spare %s ready for incumbent %s",
                    reservation.spare, reservation.incumbent)
        return "ready"

    def _pick_spare(self, incumbent: str,
                    state: "ClusterUpgradeState",
                    reserved: "set[str]") -> "Optional[object]":
        """Deterministic spare choice: the first upgrade-done, ready,
        schedulable, unreserved node by name — typically a
        just-finished node of the same wave."""
        reserve_key = self.keys.prewarm_reservation_annotation
        candidates = [
            ns.node for ns in state.bucket(UpgradeState.DONE)
            if ns.node.metadata.name != incumbent
            and ns.node.metadata.name not in reserved
            and ns.node.is_ready()
            and not ns.node.is_unschedulable()
            and reserve_key not in ns.node.metadata.annotations]
        if not candidates:
            return None
        return min(candidates, key=lambda n: n.metadata.name)

    # ------------------------------------------------------------------
    # release
    # ------------------------------------------------------------------
    def sweep(self, state: "ClusterUpgradeState") -> None:
        """Release reservations whose incumbent finished (or vanished):
        the incumbent is back serving its model, so the replacement
        replica may retire. Run every pass — this is also the
        crash-residue sweep: a fresh incarnation releases stamps its
        predecessor died holding."""
        by_name: dict[str, str] = {}
        for label, bucket in state.node_states.items():
            for ns in bucket:
                by_name[ns.node.metadata.name] = label
        done = str(UpgradeState.DONE)
        for reservation in self.reservations(state).values():
            incumbent_state = by_name.get(reservation.incumbent)
            if incumbent_state is None or incumbent_state == done:
                self._release_one(reservation, rule="incumbent-done")
                continue
            spare_state = by_name.get(reservation.spare)
            if spare_state != done:
                # the spare was drafted into a new rollout (a revision
                # bump re-marked it): it can no longer host a stable
                # replacement replica — release so a fresh spare can
                # be reserved
                self._release_one(reservation, rule="spare-recycled")

    def _release_one(self, reservation: _Reservation,
                     rule: str) -> None:
        """Delete BOTH prewarm stamps on one merge patch (crash-atomic:
        there is no window where only one remains)."""
        self.provider.change_node_upgrade_annotations(
            reservation.spare_node,
            {self.keys.prewarm_reservation_annotation: None,
             self.keys.prewarm_ready_annotation: None})
        self.released_total += 1
        self._audit(reservation.spare, "release", rule,
                    {"incumbent": reservation.incumbent,
                     "model": reservation.model})
        if self.release is not None:
            try:
                self.release(reservation.spare, reservation.incumbent)
            except Exception as exc:  # noqa: BLE001 — deployment seam
                logger.warning("prewarm release hook raised for spare "
                               "%s: %s", reservation.spare, exc)
        logger.info("prewarm: released spare %s (incumbent %s, %s)",
                    reservation.spare, reservation.incumbent, rule)


class DisruptionCostRanker:
    """Spend the disruption budget on the cheapest serving disruption
    first; hold sole-replica interactive nodes behind the prewarm arc.

    Wraps the planner chain OUTERMOST and keeps every admission
    decision with the inner chain: candidates are bucketed into cost
    tiers and the inner planner is invoked tier by tier with the
    remaining budget, so cheap tiers are exhausted before expensive
    ones regardless of how the inner chain (LPT, slice atomicity,
    canary cohort) orders within a tier.
    """

    #: tier indices (for status/tests)
    TIER_IDLE = 0          # serving nothing
    TIER_CHEAP = 1         # batch-only, replication preserved
    TIER_INTERACTIVE = 2   # interactive served, replication preserved
    TIER_SOLE_BATCH = 3    # would leave a relaxed-SLO model dark

    def __init__(self, inner: "UpgradePlanner",
                 source: "Callable[[], dict]",
                 classes: "dict[str, TrafficClassSpec]",
                 prewarm: Optional[PrewarmCoordinator] = None,
                 audit: Optional[AuditHook] = None,
                 at_risk_annotation: Optional[str] = None) -> None:
        self.inner = inner
        self._source = source
        self.classes = classes
        self.prewarm = prewarm
        self.audit = audit
        # Annotation key (RemediationKeys.at_risk_annotation) marking
        # nodes the precursor model condemned at risk: their drain is
        # already planned by the remediation arc, so when a rollout
        # must disrupt someone anyway, they are the cheapest candidates.
        self.at_risk_annotation = at_risk_annotation
        self._last_at_risk: set[str] = set()
        #: node -> (rule, inputs) of the most recent pass's holds —
        #: consumed by the audit wrapper and the explain chain.
        self.last_holds: "dict[str, tuple[str, dict]]" = {}
        #: Status block of the most recent ranked plan
        #: (cluster_status["capacity"]["ranker"] feed).
        self.last_rank: Optional[dict] = None
        #: lifetime counters (metrics feed)
        self.holds_total = 0
        self.ranked_passes_total = 0

    def _sample(self) -> "Optional[dict[str, list]]":
        try:
            mapping = self._source()
        except Exception as exc:  # noqa: BLE001 — signal boundary:
            # a broken source degrades to class-blind, never wedges
            logger.warning("disruption ranker endpoint source raised "
                           "(%s); planning class-blind", exc)
            return None
        return dict(mapping) if mapping else None

    def _class(self, name: str) -> "object":
        spec = self.classes.get(name)
        if spec is not None:
            return spec
        from tpu_operator_libs.api.upgrade_policy import (
            TrafficClassSpec,
        )

        # an endpoint declaring an unlisted class ranks as a relaxed
        # (non-interactive) class with the default replication floor
        return TrafficClassSpec(name=name)

    def plan(self, candidates: "list[NodeUpgradeState]", available: int,
             state: "ClusterUpgradeState") -> "list[NodeUpgradeState]":
        mapping = self._sample()
        if mapping is None:
            # fail open: no serving signal, class-blind inner plan
            self.last_holds = {}
            self.last_rank = None
            return self.inner.plan(candidates, available, state)
        self.ranked_passes_total += 1
        # Replicas on nodes already COMMITTED to going down must not
        # count toward a model's replication: a node in cordon-required
        # still admits until the gate flips it, yet its drain is
        # already decided — counting it would let a replicated pair's
        # second member drain in the very next wave and darken the
        # model (the SlicePlanner's committed_down rule, per model).
        committed_down = {
            ns.node.metadata.name
            for st in IN_PROGRESS_STATES
            for ns in state.bucket(st)}
        # model -> admitting replica count over endpoints that are
        # neither draining nor on a committed-down node (prewarmed
        # replacement replicas included — that is exactly how a
        # completed prewarm lifts its hold)
        model_admitting: dict[str, int] = {}
        for node_name, endpoints in mapping.items():
            if node_name in committed_down:
                continue
            for ep in endpoints:
                if ep.model and not ep.draining:
                    model_admitting[ep.model] = \
                        model_admitting.get(ep.model, 0) + 1

        # first sweep: cost tiers from class/in-flight alone
        tiers: "list[list[NodeUpgradeState]]" = [[], [], [], []]
        load: dict[str, int] = {}
        at_risk_ranked: set[str] = set()
        for ns in candidates:
            name = ns.node.metadata.name
            endpoints = mapping.get(name) or ()
            tier = self.TIER_IDLE
            in_flight = 0
            for ep in endpoints:
                in_flight += ep.in_flight
                spec = self._class(ep.traffic_class)
                if getattr(spec, "interactive", False):
                    if tier < self.TIER_INTERACTIVE:
                        tier = self.TIER_INTERACTIVE
                elif tier < self.TIER_CHEAP:
                    tier = self.TIER_CHEAP
                if ep.model and not ep.draining \
                        and not getattr(spec, "interactive", False) \
                        and model_admitting.get(ep.model, 0) - 1 \
                        < spec.min_replicas:
                    tier = self.TIER_SOLE_BATCH
            if self.at_risk_annotation is not None \
                    and self.at_risk_annotation \
                    in ns.node.metadata.annotations:
                # condemned-at-risk (predicted failure): leaving anyway,
                # so it outranks every serving tier — spend the budget
                # on the node the fleet is about to lose regardless
                tier = self.TIER_IDLE
                at_risk_ranked.add(name)
            load[name] = in_flight
            tiers[tier].append(ns)
        # within a tier, fewer live generations drain cheaper; the
        # sort is stable so equal loads keep the candidates' input
        # order (cold tier == inner order, the PR 9 degradation rule)
        for bucket in tiers:
            bucket.sort(key=lambda ns: load[ns.node.metadata.name])

        # second sweep, tier by tier: the replication-floor check runs
        # SEQUENTIALLY with optimistic decrements, so two replicas of
        # one model can never pass the floor in the same plan — the
        # second is held this pass and re-evaluated once the first is
        # done (worst case: one deferred wave, never a dark model).
        holds: "dict[str, tuple[str, dict]]" = {}
        selected: "list[NodeUpgradeState]" = []
        remaining = available
        for bucket in tiers:
            eligible: "list[NodeUpgradeState]" = []
            for ns in bucket:
                name = ns.node.metadata.name
                hold = self._floor_hold(name, mapping.get(name) or (),
                                        model_admitting, state)
                if hold is not None:
                    holds[name] = hold
                    continue
                for ep in mapping.get(name) or ():
                    if ep.model and not ep.draining:
                        model_admitting[ep.model] = \
                            model_admitting.get(ep.model, 0) - 1
                eligible.append(ns)
            if not eligible:
                continue
            picked = self.inner.plan(eligible, max(0, remaining), state)
            selected.extend(picked)
            remaining -= sum(
                1 for ns in picked if not ns.node.is_unschedulable())
        for name, hold in holds.items():
            if hold != self.last_holds.get(name):
                # audit on rule/arc CHANGE only (the dedup the
                # DecisionAudit hold path applies, kept here so a
                # pass-stable hold is one fact, not one per pass)
                self.holds_total += 1
                if self.audit is not None:
                    self.audit("hold", name, "hold", hold[0], hold[1])
                logger.info(
                    "disruption ranker holding node %s: %s (%s)",
                    name, hold[0], hold[1])
        self.last_holds = holds
        for name in sorted(at_risk_ranked - self._last_at_risk):
            # audit on first sight only (the hold path's change-dedup):
            # a pass-stable at-risk ranking is one fact, not one per pass
            if self.audit is not None:
                self.audit("rank", name, "tier-idle", RANK_AT_RISK,
                           {"annotation": self.at_risk_annotation})
            logger.info("disruption ranker promoting at-risk node %s "
                        "to the cheapest tier", name)
        self._last_at_risk = at_risk_ranked
        self.last_rank = {
            "tiers": [len(bucket) for bucket in tiers],
            "held": len(holds),
            "selected": len(selected),
        }
        if at_risk_ranked:
            self.last_rank["atRisk"] = len(at_risk_ranked)
        return selected

    def _floor_hold(self, name: str, endpoints: "tuple | list",
                    model_admitting: "dict[str, int]",
                    state: "ClusterUpgradeState",
                    ) -> "Optional[tuple[str, dict]]":
        """(rule, inputs) when draining ``name`` now would take an
        interactive model below its class replication floor; drives
        the prewarm arc for the held model. None = free to drain."""
        for ep in endpoints:
            if not ep.model or ep.draining:
                continue
            spec = self._class(ep.traffic_class)
            if not getattr(spec, "interactive", False):
                continue
            others = model_admitting.get(ep.model, 0) - 1
            if others >= spec.min_replicas:
                continue
            arc = "none"
            if self.prewarm is not None:
                arc = self.prewarm.ensure(
                    name, ep.model, spec.name, state)
            rule = (HOLD_AWAITING_PREWARM
                    if arc in ("reserved", "warming")
                    else HOLD_SOLE_REPLICA)
            return rule, {"model": ep.model, "class": spec.name,
                          "prewarm": arc}
        return None
