"""RolloutGuard: canary verdicts, fleet halt and revision quarantine.

The reference library upgrades every node to the new DaemonSet revision
with no notion of "the new revision itself is bad": ``FAILED`` is a
per-node dead end, and a broken libtpu build takes out the whole fleet
one ``maxUnavailable`` batch at a time. This guard closes that hole:

1. **Canary waves.** With ``CanaryRolloutSpec.enable`` the first
   ``canaryCount`` nodes (a deterministic cohort derived from sorted
   node names, so a restarted operator recomputes the identical set
   from cluster state alone) upgrade first; every other node waits
   until the whole cohort is ``upgrade-done`` on the new revision AND
   ``bakeSeconds`` have elapsed since (the bake stamp is a DaemonSet
   annotation — durable, crash-safe).
2. **Verdicts & halt.** Per revision, the guard aggregates failure
   verdicts: a node whose runtime pod carries the revision and is in
   ``upgrade-failed`` (validation timeout, drain failure) or whose pod
   is crash-looping past the restart threshold. At
   ``failureThreshold`` verdicts the fleet HALTS — the revision hash is
   written to the DaemonSet's quarantine annotation in ONE patch (the
   durable halt commit), and the state manager stops admitting nodes
   into the upgrade flow and stops restarting pods onto the hash.
3. **Rollback.** With ``RollbackSpec.enable`` the guard re-pins the
   previous ControllerRevision (``kubectl rollout undo`` semantics via
   ``K8sClient.rollback_daemon_set``); the state manager then drives
   every node stuck on the condemned hash through
   ``rollback-required`` (pod delete → restart on the old revision →
   revalidate → uncordon). The quarantine annotation OUTLIVES the
   rollback: reconcile never re-attempts the hash, because a changed DS
   spec produces a different hash.

Everything durable lives on the DaemonSet (quarantine + bake stamps);
the guard object itself only carries metrics accumulators, so a crash
loses at most one histogram sample, never a safety decision.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

from tpu_operator_libs.api.upgrade_policy import (
    CanaryRolloutSpec,
    RollbackSpec,
    UpgradePolicySpec,
    scaled_value_from_int_or_percent,
)
from tpu_operator_libs.consts import (
    POD_CONTROLLER_REVISION_HASH_LABEL,
    TRUE_STRING,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    K8sClient,
    NotFoundError,
)
from tpu_operator_libs.k8s.objects import DaemonSet
from tpu_operator_libs.upgrade.pod_manager import RevisionHashError
from tpu_operator_libs.util import Clock, Event, EventRecorder, log_event

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (state_manager
    # imports this module; only type names flow the other way)
    from tpu_operator_libs.upgrade.pod_manager import PodManager
    from tpu_operator_libs.upgrade.state_manager import ClusterUpgradeState

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class RolloutDecision:
    """One pass's verdict, consumed by ``apply_state``.

    ``halted`` freezes the fleet: no node newly enters
    ``upgrade-required``, no admission to ``cordon-required``, and no
    pod restart toward a hash in ``quarantined``. ``canary_active``
    restricts admission to ``cohort`` (the canary wave). ``quarantined``
    also drives the per-node rollback transitions — it persists after
    the halt lifts, which is what keeps a condemned hash condemned.
    """

    canary_active: bool = False
    cohort: frozenset[str] = frozenset()
    halted: bool = False
    #: Revision hashes condemned by annotation (whether or not they are
    #: still the DS's newest — i.e. whether the halt is still in force).
    quarantined: frozenset[str] = frozenset()
    #: Quarantined hashes that are STILL the update revision: restarts
    #: toward these must be suppressed (rollback pending or disabled).
    quarantined_active: frozenset[str] = frozenset()
    #: Failure verdicts counted for the newest revision this pass.
    failure_verdicts: int = 0
    #: Why admissions are gated, for status/debugging.
    reason: str = ""


@dataclass
class ShardedCanaryContext:
    """Fleet-wide canary inputs under the sharded control plane's
    partition-scoped reads (no fleet pod join exists).

    ``eligible`` is the sorted, skip-filtered ``(node_name, pool)``
    cohort domain derived from node metadata (see
    ``ClusterUpgradeStateManager._sharded_canary_context``); ``view``
    is the replica's shard view (``ring`` + ``owned_shards``). With a
    context installed the guard verifies cohort completion through
    durable PER-SHARD attestation stamps on the DaemonSet: each shard's
    owner stamps the revision once every cohort member in its shard is
    done-on-newest (pod hash checked against the partition it actually
    holds), and the fleet-wide canary-passed stamp is only written once
    every cohort-bearing shard attests — so no replica ever has to see
    another partition's pods, and no replica can open the fleet waves
    on members it cannot verify.
    """

    view: object
    eligible: "list[tuple[str, str]]"


@dataclass
class _DsRollout:
    """Per-DaemonSet working set for one assessment."""

    ds: DaemonSet
    newest: str
    quarantined: Optional[str]
    failures: "list[str]" = field(default_factory=list)


class RolloutGuard:
    """Fleet-level canary/halt/rollback brain, one per state manager."""

    def __init__(self, client: K8sClient, keys: UpgradeKeys,
                 recorder: Optional[EventRecorder] = None,
                 clock: Optional[Clock] = None,
                 pod_failure_threshold: int = 10) -> None:
        self._client = client
        self._keys = keys
        self._recorder = recorder
        self._clock = clock or Clock()
        self._pod_failure_threshold = pod_failure_threshold
        #: Optional ReconcileNudger (installed by the state manager):
        #: bake expiry is a pure-time deadline with no cluster event, so
        #: without a timer-wheel wakeup the fleet waves only start at
        #: whatever pass happens to run after the bake elapses.
        self.nudger = None
        #: Lifetime failure verdicts observed, deduplicated per
        #: (revision, node) — a crash-looping canary is one verdict, not
        #: one per reconcile pass.
        self.canary_failure_verdicts_total = 0
        self._verdicts_seen: set[tuple[str, str]] = set()
        #: Fleet halts committed (quarantine annotations written).
        self.halts_total = 0
        #: DaemonSet rollbacks (previous revision re-pins) issued.
        self.rollbacks_started_total = 0
        #: Halted revisions fully evacuated: the halt lifted and no pod
        #: carries the hash any more.
        self.rollbacks_completed_total = 0
        #: Wall-clock (virtual) halt→evacuated durations, drained by
        #: metrics.observe_rollout. In-memory: a crash loses the sample,
        #: never the rollback itself.
        self._rollback_durations: list[float] = []
        self._halt_started_at: dict[str, float] = {}
        #: Partition-reads canary inputs for the CURRENT assessment
        #: (None outside sharded partition mode) — set per assess().
        self._shard_context: Optional[ShardedCanaryContext] = None
        self.last_decision = RolloutDecision()
        #: Policy-engine verdict seam (the ``canary.verdict``
        #: OBSERVATION hook, policy/engine.py): ``fn(node, revision,
        #: pod) -> bool`` — True contributes one failure verdict for
        #: the node on the revision under test, exactly like the
        #: machine's own FAILED-bucket signal. Fail-open: the engine
        #: returns False on any program error (audited there), so a
        #: bad policy can never halt a fleet by crashing.
        self.extra_verdict = None

    def drain_rollback_durations(self) -> "list[float]":
        out, self._rollback_durations = self._rollback_durations, []
        return out

    # ------------------------------------------------------------------
    # assessment (runs first in every apply_state pass)
    # ------------------------------------------------------------------
    def assess(self, state: "ClusterUpgradeState",
               policy: UpgradePolicySpec,
               pod_manager: "PodManager",
               shard_context: Optional[ShardedCanaryContext] = None,
               ) -> RolloutDecision:
        """Evaluate verdicts, commit halts/rollbacks, return the pass
        decision. ``pod_manager`` is passed per call (not captured at
        construction) because ``with_pod_deletion_enabled`` rebuilds the
        state manager's instance and the revision memo must be the
        per-snapshot one. ``shard_context`` switches cohort derivation
        and completion checks to the partition-reads protocol (see
        :class:`ShardedCanaryContext`); verdicts are always collected
        from ``state`` — under sharding that is the replica's own
        partition, and the halt/quarantine commits are durable DS
        annotations every replica re-reads, so one partition's verdict
        threshold halts the whole fleet."""
        self._shard_context = shard_context
        canary = policy.canary
        if canary is None or not canary.enable:
            self.last_decision = RolloutDecision()
            return self.last_decision
        rollback = policy.rollback or RollbackSpec()

        rollouts = self._collect(state, pod_manager)
        if not rollouts:
            self.last_decision = RolloutDecision()
            return self.last_decision

        quarantined: set[str] = set()
        quarantined_active: set[str] = set()
        halted = False
        failure_verdicts = 0
        reason = ""
        for ro in rollouts.values():
            failure_verdicts += len(ro.failures)
            for node_name in ro.failures:
                if (ro.newest, node_name) not in self._verdicts_seen:
                    self._verdicts_seen.add((ro.newest, node_name))
                    self.canary_failure_verdicts_total += 1
            if (ro.quarantined is None
                    and len(ro.failures) >= canary.failure_threshold):
                self._halt(ro)
            if ro.quarantined is not None:
                quarantined.add(ro.quarantined)
                if ro.quarantined == ro.newest:
                    # halt in force: the DS still points at the bad hash
                    halted = True
                    quarantined_active.add(ro.quarantined)
                    reason = (f"halted: revision {ro.quarantined!r} "
                              f"quarantined")
                    if rollback.enable:
                        self._rollback(ro, pod_manager)
                else:
                    self._maybe_complete(ro, state, pod_manager)

        cohort: frozenset[str] = frozenset()
        canary_active = False
        if not halted:
            cohort, canary_active = self._canary_wave(
                state, canary, rollouts)
            if canary_active:
                reason = (f"canary wave: {len(cohort)} node(s) probing "
                          f"the new revision")
        self.last_decision = RolloutDecision(
            canary_active=canary_active, cohort=cohort, halted=halted,
            quarantined=frozenset(quarantined),
            quarantined_active=frozenset(quarantined_active),
            failure_verdicts=failure_verdicts, reason=reason)
        return self.last_decision

    # ------------------------------------------------------------------
    # verdict collection
    # ------------------------------------------------------------------
    def _collect(self, state: "ClusterUpgradeState",
                 pod_manager: "PodManager") -> "dict[str, _DsRollout]":
        rollouts: dict[str, _DsRollout] = {}
        quarantine_key = self._keys.quarantined_revision_annotation
        for bucket_label, bucket in state.node_states.items():
            for ns in bucket:
                ds = ns.runtime_daemon_set
                if ds is None:
                    continue  # orphaned pods have no revision to judge
                ro = rollouts.get(ds.metadata.uid)
                if ro is None:
                    try:
                        newest = pod_manager.get_daemon_set_revision_hash(ds)
                    except (RevisionHashError, ApiServerError,
                            ConflictError) as exc:
                        logger.warning(
                            "rollout guard cannot resolve newest revision "
                            "of %s; skipping this pass: %s",
                            ds.metadata.name, exc)
                        continue
                    ro = _DsRollout(
                        ds=ds, newest=newest,
                        quarantined=ds.metadata.annotations.get(
                            quarantine_key))
                    rollouts[ds.metadata.uid] = ro
                try:
                    pod_hash = pod_manager.get_pod_revision_hash(
                        ns.runtime_pod)
                except RevisionHashError:
                    continue
                if pod_hash != ro.newest:
                    continue
                if bucket_label == str(UpgradeState.FAILED):
                    # FAILED on the revision under test — the machine
                    # already folded crash-loops and validation
                    # timeouts into this state, so it is the one
                    # verdict signal (in VALIDATION_REQUIRED, a
                    # crash-looping pod is merely "not yet ready" until
                    # its timeout fails the node)
                    ro.failures.append(ns.node.metadata.name)
                elif (bucket_label == str(UpgradeState.ROLLBACK_REQUIRED)
                        and ns.runtime_pod.is_failing(
                            self._pod_failure_threshold)):
                    # a node already rolling back that STILL carries a
                    # crash-looping pod of the newest revision keeps
                    # its verdict standing (it was FAILED a pass ago)
                    ro.failures.append(ns.node.metadata.name)
                elif self.extra_verdict is not None:
                    # the policy engine's canary.verdict observation
                    # hook: a user program may condemn the node on this
                    # revision from signals the machine cannot see
                    # (fail-open inside the engine — never raises)
                    if self.extra_verdict(ns.node, ro.newest,
                                          ns.runtime_pod):
                        ro.failures.append(ns.node.metadata.name)
        return rollouts

    # ------------------------------------------------------------------
    # halt / rollback commits
    # ------------------------------------------------------------------
    def _halt(self, ro: _DsRollout) -> None:
        """Condemn ``ro.newest``: ONE annotation patch is the durable
        halt commit (crash before it = re-derived next pass; crash after
        = the halt holds)."""
        ds = ro.ds
        try:
            fresh = self._client.patch_daemon_set_annotations(
                ds.metadata.namespace, ds.metadata.name,
                {self._keys.quarantined_revision_annotation: ro.newest})
        except (ApiServerError, ConflictError, NotFoundError) as exc:
            logger.warning("failed to commit fleet halt for %s revision "
                           "%s; retrying next pass: %s",
                           ds.metadata.name, ro.newest, exc)
            return
        ds.metadata.annotations = fresh.metadata.annotations
        ro.quarantined = ro.newest
        self.halts_total += 1
        self._halt_started_at.setdefault(ro.newest, self._clock.now())
        logger.warning(
            "FLEET HALT: revision %s of DaemonSet %s/%s quarantined "
            "(%d failure verdict(s) >= threshold)", ro.newest,
            ds.metadata.namespace, ds.metadata.name, len(ro.failures))
        log_event(self._recorder, ds, Event.WARNING,
                  self._keys.event_reason,
                  f"Fleet halted: revision {ro.newest} quarantined after "
                  f"{len(ro.failures)} canary failure verdict(s) "
                  f"({', '.join(sorted(ro.failures))})")

    def _rollback(self, ro: _DsRollout,
                  pod_manager: "PodManager") -> None:
        """Re-pin the previous ControllerRevision. Idempotent: a crash
        between halt and rollback re-attempts here next pass."""
        ds = ro.ds
        try:
            previous = pod_manager.get_previous_daemon_set_revision_hash(ds)
        except (ApiServerError, ConflictError) as exc:
            logger.warning("cannot resolve previous revision of %s; "
                           "retrying next pass: %s", ds.metadata.name, exc)
            return
        if previous is None:
            logger.error(
                "DaemonSet %s has no previous ControllerRevision to roll "
                "back to; fleet stays halted for manual action",
                ds.metadata.name)
            return
        try:
            self._client.rollback_daemon_set(
                ds.metadata.namespace, ds.metadata.name, previous)
        except NotImplementedError:
            logger.error(
                "cluster backend cannot roll back DaemonSets; fleet "
                "stays halted for manual action")
            return
        except (ApiServerError, ConflictError, NotFoundError) as exc:
            logger.warning("failed to roll back %s to revision %s; "
                           "retrying next pass: %s",
                           ds.metadata.name, previous, exc)
            return
        # the revision ordering changed mid-snapshot: the per-snapshot
        # memo would keep answering with the condemned hash for the rest
        # of this pass, freezing the rollback transitions a full tick
        pod_manager.reset_revision_cache()
        self.rollbacks_started_total += 1
        logger.warning(
            "ROLLBACK: DaemonSet %s/%s re-pinned to previous revision %s "
            "(quarantined: %s)", ds.metadata.namespace, ds.metadata.name,
            previous, ro.quarantined)
        log_event(self._recorder, ds, Event.NORMAL,
                  self._keys.event_reason,
                  f"Rolled DaemonSet back to previous revision {previous} "
                  f"(revision {ro.quarantined} quarantined)")

    def _maybe_complete(self, ro: _DsRollout,
                        state: "ClusterUpgradeState",
                        pod_manager: "PodManager") -> None:
        """Close the books on a lifted halt: once no runtime pod carries
        the condemned hash and no node is mid-rollback, record the
        halt→evacuated duration."""
        started = self._halt_started_at.get(ro.quarantined or "")
        if started is None:
            return
        if state.bucket(UpgradeState.ROLLBACK_REQUIRED):
            return
        for bucket in state.node_states.values():
            for ns in bucket:
                try:
                    if pod_manager.get_pod_revision_hash(
                            ns.runtime_pod) == ro.quarantined:
                        return
                except RevisionHashError:
                    continue
        del self._halt_started_at[ro.quarantined or ""]
        self.rollbacks_completed_total += 1
        self._rollback_durations.append(self._clock.now() - started)
        logger.info("rollback complete: no pod carries quarantined "
                    "revision %s any more", ro.quarantined)

    # ------------------------------------------------------------------
    # canary wave
    # ------------------------------------------------------------------
    def canary_cohort(self, state: "ClusterUpgradeState",
                      canary: CanaryRolloutSpec) -> frozenset[str]:
        """The deterministic canary cohort: first ``canaryCount`` of the
        managed node names in sorted order, skip-labeled nodes excluded
        (they would park the canary phase forever). Pure in the
        snapshot, so every operator incarnation derives the same set.
        Under partition reads the domain comes from the shard context
        (node metadata, fleet-wide) instead of the snapshot's pod join
        (partition-scoped by construction)."""
        if self._shard_context is not None:
            eligible = [name for name, _ in self._shard_context.eligible]
        else:
            eligible = sorted(
                node.metadata.name for node in state.all_nodes()
                if node.metadata.labels.get(self._keys.skip_label)
                != TRUE_STRING)
        if not eligible:
            return frozenset()
        count = max(1, scaled_value_from_int_or_percent(
            canary.canary_count, len(eligible), round_up=True))
        return frozenset(eligible[:count])

    def _canary_wave(self, state: "ClusterUpgradeState",
                     canary: CanaryRolloutSpec,
                     rollouts: "dict[str, _DsRollout]",
                     ) -> tuple[frozenset[str], bool]:
        """(cohort, canary_active): active while the cohort has not yet
        proven the newest revision (done + baked)."""
        cohort = self.canary_cohort(state, canary)
        if not cohort:
            return cohort, False
        # one runtime DS per managed namespace is the deployed shape;
        # with several, the wave gates on ALL of them having baked
        for ro in rollouts.values():
            if not self._revision_baked(state, ro, cohort, canary):
                return cohort, True
        return cohort, False

    def _revision_baked(self, state: "ClusterUpgradeState",
                        ro: _DsRollout, cohort: frozenset[str],
                        canary: CanaryRolloutSpec) -> bool:
        """True once every cohort node is upgrade-done on ``ro.newest``
        and the bake time has elapsed since the (durable) pass stamp."""
        stamp_key = self._keys.canary_passed_annotation
        stamp = ro.ds.metadata.annotations.get(stamp_key, "")
        revision, _, passed_at = stamp.partition(":")
        if revision == ro.newest and passed_at:
            try:
                expiry = float(passed_at) + canary.bake_seconds
                baked = self._clock.now() >= expiry
                if not baked and self.nudger is not None:
                    # wake the pass that opens the fleet waves exactly
                    # at bake expiry (idempotent via slot dedup, and
                    # re-derived from the durable stamp after a crash)
                    self.nudger.nudge_at(expiry, "canary-bake")
                return baked
            except ValueError:
                pass  # corrupt stamp: fall through and re-derive
        done_on_newest: set[str] = set()
        for ns in state.bucket(UpgradeState.DONE):
            pod_hash = ns.runtime_pod.metadata.labels.get(
                POD_CONTROLLER_REVISION_HASH_LABEL, "")
            if pod_hash == ro.newest and ns.runtime_pod.is_ready():
                done_on_newest.add(ns.node.metadata.name)
        if self._shard_context is not None:
            if not self._shards_attested(ro, cohort, done_on_newest):
                return False
        elif not cohort <= done_on_newest:
            return False
        now = self._clock.now()
        try:
            fresh = self._client.patch_daemon_set_annotations(
                ro.ds.metadata.namespace, ro.ds.metadata.name,
                {stamp_key: f"{ro.newest}:{now:g}"})
            ro.ds.metadata.annotations = fresh.metadata.annotations
        except (ApiServerError, ConflictError, NotFoundError) as exc:
            logger.warning("failed to stamp canary pass for %s; retrying "
                           "next pass: %s", ro.ds.metadata.name, exc)
            return False
        if canary.bake_seconds > 0 and self.nudger is not None:
            self.nudger.nudge_at(now + canary.bake_seconds, "canary-bake")
        logger.info(
            "canary cohort %s passed on revision %s; baking %ds before "
            "fleet waves", sorted(cohort), ro.newest, canary.bake_seconds)
        log_event(self._recorder, ro.ds, Event.NORMAL,
                  self._keys.event_reason,
                  f"Canary cohort passed on revision {ro.newest}; baking "
                  f"{canary.bake_seconds}s before fleet waves")
        return canary.bake_seconds <= 0

    def _shards_attested(self, ro: _DsRollout, cohort: "frozenset[str]",
                         done_on_newest: "set[str]") -> bool:
        """Partition-reads cohort completion: attest our own shards'
        cohort members (verifiable against the pods this replica
        holds), then require every cohort-bearing shard's durable
        attestation to match ``ro.newest``.

        The stamps are per-shard DaemonSet annotation keys (the
        budget-share ledger idiom: disjoint keys, concurrent owners'
        merge patches compose) valued with the revision hash, so a new
        rollout ignores the previous rollout's attestations, and an
        owner crash between attesting and the fleet stamp re-derives
        from cluster state alone."""
        ctx = self._shard_context
        pool_of = dict(ctx.eligible)
        ring = ctx.view.ring
        by_shard: dict[int, set[str]] = {}
        for name in cohort:
            shard = ring.shard_for(name, pool_of.get(name, ""))
            by_shard.setdefault(shard, set()).add(name)
        prefix = self._keys.canary_shard_passed_prefix
        annotations = ro.ds.metadata.annotations
        owned = ctx.view.owned_shards()
        for shard in sorted(by_shard):
            if shard not in owned:
                continue
            key = f"{prefix}{shard}"
            if annotations.get(key) == ro.newest:
                continue
            if not by_shard[shard] <= done_on_newest:
                continue
            try:
                fresh = self._client.patch_daemon_set_annotations(
                    ro.ds.metadata.namespace, ro.ds.metadata.name,
                    {key: ro.newest})
                ro.ds.metadata.annotations = fresh.metadata.annotations
                annotations = ro.ds.metadata.annotations
                logger.info(
                    "canary shard %d attested on revision %s (%s)",
                    shard, ro.newest, sorted(by_shard[shard]))
            except (ApiServerError, ConflictError, NotFoundError) as exc:
                logger.warning("failed to attest canary shard %d; "
                               "retrying next pass: %s", shard, exc)
        return all(annotations.get(f"{prefix}{shard}") == ro.newest
                   for shard in by_shard)

    def status(self) -> dict:
        """CRD-embeddable rollout block for the last assessed pass."""
        decision = self.last_decision
        out: dict = {}
        if decision.halted:
            out["halted"] = True
        if decision.quarantined:
            out["quarantinedRevisions"] = sorted(decision.quarantined)
        if decision.canary_active:
            out["canaryWave"] = sorted(decision.cohort)
        if decision.reason:
            out["reason"] = decision.reason
        return out
