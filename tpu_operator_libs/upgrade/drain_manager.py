"""DrainManager: async node drain (reference drain_manager.go:32-155).

One worker per node, deduplicated by an in-flight set; the worker cordons,
drains, then commits the outcome as the node's next state label
(pod-restart-required on success, upgrade-failed on any failure). The state
write is the only side channel back to the state machine — the reconcile
loop discovers the result on its next pass.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from tpu_operator_libs.api.upgrade_policy import DrainSpec
from tpu_operator_libs.consts import UpgradeState
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    K8sClient,
)
from tpu_operator_libs.k8s.drain import DrainHelper, run_cordon_or_uncordon
from tpu_operator_libs.k8s.objects import Node
from tpu_operator_libs.upgrade.gate import EvictionGate
from tpu_operator_libs.upgrade.state_provider import NodeUpgradeStateProvider
from tpu_operator_libs.util import (
    Clock,
    Event,
    EventRecorder,
    NameSet,
    Worker,
    log_event,
)

logger = logging.getLogger(__name__)


@dataclass
class DrainConfiguration:
    """Drain spec plus target nodes (drain_manager.go:33-36)."""

    spec: Optional[DrainSpec]
    nodes: list[Node] = field(default_factory=list)


class DrainManager:
    def __init__(self, client: K8sClient,
                 provider: NodeUpgradeStateProvider,
                 recorder: Optional[EventRecorder] = None,
                 clock: Optional[Clock] = None,
                 worker: Optional[Worker] = None,
                 eviction_gate: Optional[EvictionGate] = None) -> None:
        self._client = client
        self._provider = provider
        self._recorder = recorder
        self._clock = clock or Clock()
        self._worker = worker or Worker()
        self._draining_nodes = NameSet()
        # Same veto as PodManager's eviction_gate: drain must not destroy
        # a workload whose checkpoint is not yet durable — otherwise the
        # pod-deletion→drain fallback would bypass the durability
        # guarantee entirely. Shared semantics via GateKeeper.
        from tpu_operator_libs.upgrade.gate import GateKeeper

        self._gatekeeper = GateKeeper(provider.keys, recorder, "drain")
        self._gatekeeper.set_gate(eviction_gate)
        self._keys = provider.keys

    @property
    def eviction_gate(self) -> Optional["EvictionGate"]:
        return self._gatekeeper.gate

    def set_eviction_gate(self, gate: Optional["EvictionGate"]) -> None:
        self._gatekeeper.set_gate(gate)

    def abandon_stale_gate_deferrals(self, still_wanted: "set[str]") -> None:
        """Hand gate-parked nodes that left every eviction-wanting state
        back to the gate's ``release`` hook (GateKeeper.abandon_stale)."""
        self._gatekeeper.abandon_stale(still_wanted)

    def schedule_nodes_drain(self, config: DrainConfiguration) -> None:
        """Schedule an async drain per node (drain_manager.go:58-138)."""
        if not config.nodes:
            logger.info("no nodes scheduled to drain")
            return
        spec = config.spec
        if spec is None:
            raise ValueError("drain spec should not be empty")
        if not spec.enable:
            logger.info("drain is disabled")
            return

        helper = DrainHelper(
            client=self._client,
            force=spec.force,
            # TPU runtime pods are DaemonSet-owned, like the reference's
            # OFED driver pods (drain_manager.go:80-82) — never drain them.
            ignore_all_daemon_sets=True,
            delete_empty_dir_data=spec.delete_empty_dir,
            timeout_seconds=spec.timeout_seconds,
            pod_selector=spec.pod_selector,
            on_pod_deleted=lambda pod: logger.info(
                "evicted pod %s/%s", pod.namespace, pod.name),
            clock=self._clock,
        )

        for node in config.nodes:
            if not self._draining_nodes.add(node.metadata.name):
                logger.info("node %s is already being drained, skipping",
                            node.metadata.name)
                continue
            logger.info("schedule drain for node %s", node.metadata.name)
            log_event(self._recorder, node, Event.NORMAL,
                      self._keys.event_reason, "Scheduling drain of the node")
            self._worker.submit(lambda n=node: self._drain_node(n, helper))

    def _drain_node(self, node: Node, helper: DrainHelper) -> None:
        name = node.metadata.name
        try:
            if self._gatekeeper.gate is not None:
                try:
                    pods, _ = helper.get_pods_for_deletion(name)
                except Exception as exc:  # noqa: BLE001 — worker boundary
                    # Cannot even enumerate pods (transient API error):
                    # park in drain-required and retry next reconcile —
                    # delay, never escalate.
                    logger.warning("could not enumerate pods for gate on "
                                   "node %s; deferring drain: %s",
                                   name, exc)
                    return
                # Park in drain-required until the gate opens; a raising
                # gate only delays, never escalates (GateKeeper semantics).
                if not self._gatekeeper.allows(node, pods):
                    return
            try:
                run_cordon_or_uncordon(self._client, name, True)
            except (ApiServerError, ConflictError) as exc:
                # Transient apiserver failure: marking the node
                # upgrade-failed would strand it (its pod is out of sync,
                # so auto-recovery can never fire). Stay drain-required
                # and let the next reconcile retry.
                logger.warning("transient error cordoning node %s; "
                               "deferring drain: %s", name, exc)
                return
            except Exception as exc:  # noqa: BLE001 — worker boundary
                logger.error("failed to cordon node %s: %s", name, exc)
                self._fail(node, f"Failed to cordon the node: {exc}")
                return
            logger.info("cordoned node %s", name)
            try:
                helper.run_node_drain(name)
            except (ApiServerError, ConflictError) as exc:
                logger.warning("transient error draining node %s; "
                               "deferring drain: %s", name, exc)
                return
            except Exception as exc:  # noqa: BLE001 — worker boundary
                logger.error("failed to drain node %s: %s", name, exc)
                self._fail(node, f"Failed to drain the node: {exc}")
                return
            logger.info("drained node %s", name)
            log_event(self._recorder, node, Event.NORMAL,
                      self._keys.event_reason, "Successfully drained the node")
            self._change_state_quietly(
                node, UpgradeState.POD_RESTART_REQUIRED)
        finally:
            self._draining_nodes.remove(name)

    def _fail(self, node: Node, message: str) -> None:
        self._change_state_quietly(node, UpgradeState.FAILED)
        log_event(self._recorder, node, Event.WARNING,
                  self._keys.event_reason, message)

    def _change_state_quietly(self, node: Node, state: UpgradeState) -> None:
        try:
            self._provider.change_node_upgrade_state(node, state)
        except Exception as exc:  # noqa: BLE001 — worker boundary
            logger.error("failed to change state of node %s to %s: %s",
                         node.metadata.name, state, exc)

    def join(self, timeout: float = 30.0) -> None:
        """Wait for in-flight drain workers (test/sim helper)."""
        self._worker.join(timeout)
