"""DrainManager: async node drain (reference drain_manager.go:32-155).

Workers run on a :class:`~tpu_operator_libs.upgrade.worker_pool.
BoundedKeyedPool` keyed by node name — per-node dedup (a node already
being drained is never scheduled twice) with a bounded thread count,
replacing the reference's unbounded one-goroutine-per-node fan-out. The
worker cordons, drains, then commits the outcome as the node's next
state label (pod-restart-required on success, upgrade-failed on any
failure). The state write is the durable side channel back to the state
machine; with a :class:`~tpu_operator_libs.upgrade.nudger.
ReconcileNudger` installed the commit also wakes the reconcile loop
immediately, and a transient-error deferral registers a backoff wakeup
instead of silently waiting out the resync interval.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

from tpu_operator_libs.api.upgrade_policy import DrainSpec
from tpu_operator_libs.consts import UpgradeState
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    K8sClient,
)
from tpu_operator_libs.k8s.drain import DrainHelper, run_cordon_or_uncordon
from tpu_operator_libs.k8s.objects import Node
from tpu_operator_libs.upgrade.gate import EvictionGate
from tpu_operator_libs.upgrade.state_provider import NodeUpgradeStateProvider
from tpu_operator_libs.upgrade.worker_pool import BoundedKeyedPool
from tpu_operator_libs.util import (
    Clock,
    Event,
    EventRecorder,
    Worker,
    log_event,
)

if TYPE_CHECKING:
    from tpu_operator_libs.upgrade.nudger import ReconcileNudger

logger = logging.getLogger(__name__)

#: Thread bound for the drain worker pool. A drain is dominated by
#: eviction round-trips and grace-period waits, so a small pool keeps a
#: maxUnavailable-sized wave pipelined without one thread per node.
DEFAULT_DRAIN_WORKERS = 8

#: Backoff base/cap for transient-error drain retries (seconds). The
#: schedule is deliberately jitter-free: retries feed the nudger's
#: timer wheel, which coalesces same-slot wakeups anyway, and a
#: deterministic schedule keeps the seeded harnesses replayable.
DRAIN_RETRY_BASE = 2.0
DRAIN_RETRY_MAX = 60.0


@dataclass
class DrainConfiguration:
    """Drain spec plus target nodes (drain_manager.go:33-36)."""

    spec: Optional[DrainSpec]
    nodes: list[Node] = field(default_factory=list)


class DrainManager:
    def __init__(self, client: K8sClient,
                 provider: NodeUpgradeStateProvider,
                 recorder: Optional[EventRecorder] = None,
                 clock: Optional[Clock] = None,
                 worker: Optional[Worker] = None,
                 eviction_gate: Optional[EvictionGate] = None,
                 pool: Optional[BoundedKeyedPool] = None,
                 nudger: Optional["ReconcileNudger"] = None,
                 max_workers: int = DEFAULT_DRAIN_WORKERS) -> None:
        self._client = client
        self._provider = provider
        self._recorder = recorder
        self._clock = clock or Clock()
        # `worker` is kept as the async-mode seam callers already use
        # (Worker(async_mode=False) = deterministic inline drains); the
        # execution substrate is the keyed pool either way.
        if pool is None:
            async_mode = worker.async_mode if worker is not None else True
            pool = BoundedKeyedPool(max_workers=max_workers,
                                    async_mode=async_mode,
                                    name="drain-pool")
        self._pool = pool
        self.nudger = nudger
        # per-node retry count for the transient-deferral backoff
        # wakeups; reset on any committed outcome
        self._retry_counts: dict[str, int] = {}
        # Same veto as PodManager's eviction_gate: drain must not destroy
        # a workload whose checkpoint is not yet durable — otherwise the
        # pod-deletion→drain fallback would bypass the durability
        # guarantee entirely. Shared semantics via GateKeeper.
        from tpu_operator_libs.upgrade.gate import GateKeeper

        self._gatekeeper = GateKeeper(provider.keys, recorder, "drain")
        self._gatekeeper.set_gate(eviction_gate)
        self._keys = provider.keys

    @property
    def eviction_gate(self) -> Optional["EvictionGate"]:
        return self._gatekeeper.gate

    def set_eviction_gate(self, gate: Optional["EvictionGate"]) -> None:
        self._gatekeeper.set_gate(gate)

    def abandon_stale_gate_deferrals(self, still_wanted: "set[str]") -> None:
        """Hand gate-parked nodes that left every eviction-wanting state
        back to the gate's ``release`` hook (GateKeeper.abandon_stale)."""
        self._gatekeeper.abandon_stale(still_wanted)

    def release_gate(self, node: Node, pods: "list") -> None:
        """Mid-flight abort: return one node's endpoints to admitting
        (GateKeeper.release_node — durable-label driven, so it works
        across operator crash-restarts)."""
        self._gatekeeper.release_node(node, pods)

    def schedule_nodes_drain(self, config: DrainConfiguration) -> None:
        """Schedule an async drain per node (drain_manager.go:58-138)."""
        if not config.nodes:
            logger.info("no nodes scheduled to drain")
            return
        spec = config.spec
        if spec is None:
            raise ValueError("drain spec should not be empty")
        if not spec.enable:
            logger.info("drain is disabled")
            return

        helper = DrainHelper(
            client=self._client,
            force=spec.force,
            # TPU runtime pods are DaemonSet-owned, like the reference's
            # OFED driver pods (drain_manager.go:80-82) — never drain them.
            ignore_all_daemon_sets=True,
            delete_empty_dir_data=spec.delete_empty_dir,
            timeout_seconds=spec.timeout_seconds,
            pod_selector=spec.pod_selector,
            on_pod_deleted=lambda pod: logger.info(
                "evicted pod %s/%s", pod.namespace, pod.name),
            clock=self._clock,
        )

        for node in config.nodes:
            name = node.metadata.name
            submitted = self._pool.submit(
                lambda n=node: self._drain_node(n, helper), key=name)
            if not submitted:
                logger.info("node %s is already being drained, skipping",
                            name)
                continue
            logger.info("schedule drain for node %s", name)
            log_event(self._recorder, node, Event.NORMAL,
                      self._keys.event_reason, "Scheduling drain of the node")

    # ------------------------------------------------------------------
    # wakeup plumbing
    # ------------------------------------------------------------------
    def _nudge_outcome(self, name: str) -> None:
        """An outcome (success or failure) was committed as a label:
        the retry ladder resets and the loop is woken right away."""
        self._retry_counts.pop(name, None)
        if self.nudger is not None:
            self.nudger.nudge("drain")

    def _defer_retry(self, name: str) -> None:
        """Transient error: the node stays in drain-required with no
        label write — nothing will ever wake the loop for it, so
        register a backoff wakeup (exponential, capped) instead of
        waiting out a full resync interval."""
        if self.nudger is None:
            return
        retries = self._retry_counts.get(name, 0)
        self._retry_counts[name] = retries + 1
        delay = min(DRAIN_RETRY_BASE * (2 ** retries), DRAIN_RETRY_MAX)
        self.nudger.nudge_after(delay, "drain-retry")

    def _drain_node(self, node: Node, helper: DrainHelper) -> None:
        name = node.metadata.name
        if self._gatekeeper.gate is not None:
            try:
                pods, _ = helper.get_pods_for_deletion(name)
            except Exception as exc:  # noqa: BLE001 — worker boundary
                # Cannot even enumerate pods (transient API error):
                # park in drain-required and retry on the backoff
                # wakeup — delay, never escalate.
                logger.warning("could not enumerate pods for gate on "
                               "node %s; deferring drain: %s",
                               name, exc)
                self._defer_retry(name)
                return
            # Park in drain-required until the gate opens; a raising
            # gate only delays, never escalates (GateKeeper semantics).
            if not self._gatekeeper.allows(node, pods):
                return
        try:
            run_cordon_or_uncordon(self._client, name, True)
        except (ApiServerError, ConflictError) as exc:
            # Transient apiserver failure: marking the node
            # upgrade-failed would strand it (its pod is out of sync,
            # so auto-recovery can never fire). Stay drain-required
            # and let the backoff wakeup retry.
            logger.warning("transient error cordoning node %s; "
                           "deferring drain: %s", name, exc)
            self._defer_retry(name)
            return
        except Exception as exc:  # noqa: BLE001 — worker boundary
            logger.error("failed to cordon node %s: %s", name, exc)
            self._fail(node, f"Failed to cordon the node: {exc}")
            return
        logger.info("cordoned node %s", name)
        try:
            helper.run_node_drain(name)
        except (ApiServerError, ConflictError) as exc:
            logger.warning("transient error draining node %s; "
                           "deferring drain: %s", name, exc)
            self._defer_retry(name)
            return
        except Exception as exc:  # noqa: BLE001 — worker boundary
            logger.error("failed to drain node %s: %s", name, exc)
            self._fail(node, f"Failed to drain the node: {exc}")
            return
        logger.info("drained node %s", name)
        log_event(self._recorder, node, Event.NORMAL,
                  self._keys.event_reason, "Successfully drained the node")
        self._change_state_quietly(node, UpgradeState.POD_RESTART_REQUIRED)
        self._nudge_outcome(name)

    def _fail(self, node: Node, message: str) -> None:
        self._change_state_quietly(node, UpgradeState.FAILED)
        log_event(self._recorder, node, Event.WARNING,
                  self._keys.event_reason, message)
        self._nudge_outcome(node.metadata.name)

    def _change_state_quietly(self, node: Node, state: UpgradeState) -> None:
        try:
            self._provider.change_node_upgrade_state(node, state)
        except Exception as exc:  # noqa: BLE001 — worker boundary
            logger.error("failed to change state of node %s to %s: %s",
                         node.metadata.name, state, exc)

    def join(self, timeout: float = 30.0) -> None:
        """Wait for in-flight drain workers (test/sim helper)."""
        self._pool.drain(timeout)
