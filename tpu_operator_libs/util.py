"""Concurrency primitives, clock abstraction and event helpers.

TPU-native analogue of pkg/upgrade/util.go. The reference's global mutable
``DriverName`` (util.go:87-95) is deliberately absent — key construction is
instance-scoped via :class:`tpu_operator_libs.consts.UpgradeKeys`.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Iterator, Optional

logger = logging.getLogger(__name__)


class NameSet:
    """Thread-safe set of strings.

    Used to deduplicate in-flight async work per node: a node already being
    drained / having pods evicted is never scheduled twice
    (reference StringSet, util.go:26-66; guards at drain_manager.go:103 and
    pod_manager.go:163).
    """

    def __init__(self) -> None:
        self._items: set[str] = set()
        self._lock = threading.Lock()

    def add(self, item: str) -> bool:
        """Add ``item``; returns False if it was already present.

        The test-and-set is atomic, unlike the reference's separate
        Has()+Add() calls (pod_manager.go:163-165) which race two concurrent
        reconciles into double-scheduling the same node.
        """
        with self._lock:
            if item in self._items:
                return False
            self._items.add(item)
            return True

    def remove(self, item: str) -> None:
        with self._lock:
            self._items.discard(item)

    def __contains__(self, item: str) -> bool:
        with self._lock:
            return item in self._items

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def clear(self) -> None:
        with self._lock:
            self._items.clear()


class KeyedLock:
    """Per-key mutual exclusion (reference KeyedMutex, util.go:69-85).

    Serializes access to a single node's label/annotation updates while
    letting different nodes proceed in parallel.
    """

    def __init__(self) -> None:
        self._locks: dict[str, threading.Lock] = {}
        self._guard = threading.Lock()

    def _get(self, key: str) -> threading.Lock:
        with self._guard:
            lock = self._locks.get(key)
            if lock is None:
                lock = threading.Lock()
                self._locks[key] = lock
            return lock

    def lock(self, key: str) -> "_HeldLock":
        """Acquire the lock for ``key``; usable as a context manager."""
        lock = self._get(key)
        lock.acquire()
        return _HeldLock(lock)


class _HeldLock:
    def __init__(self, lock: threading.Lock) -> None:
        self._lock = lock
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._lock.release()

    def __enter__(self) -> "_HeldLock":
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()


class Clock:
    """Injectable time source.

    The reference calls ``time.Now()`` directly inside timeout logic
    (pod_manager.go:337, validation_manager.go:141), forcing its tests to
    sleep.  All timeout handling here goes through a Clock so tests (and the
    rolling-upgrade simulator) can advance virtual time instantly.
    """

    def now(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    """Deterministic clock for tests and simulation."""

    def __init__(self, start: float = 0.0) -> None:
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)

    def advance(self, seconds: float) -> None:
        with self._lock:
            self._now += seconds


# client-go logs client-side throttling that delays a request by more
# than 1 s at warning level; mirror that.
_LONG_THROTTLE_WARN_S = 1.0


class TokenBucketRateLimiter:
    """Token bucket with client-go flowcontrol semantics.

    ``qps`` tokens accrue per second up to a capacity of ``burst``.
    :meth:`wait` always admits the caller, blocking until its
    reservation matures; concurrent waiters queue fairly because each
    reservation pushes the bucket further into debt (golang
    ``rate.Limiter`` reservation model). :meth:`try_accept` is the
    non-blocking form (client-go ``TryAccept``).

    ``now``/``sleep`` are injectable so tests drive time explicitly.
    """

    def __init__(self, qps: float = 5.0, burst: int = 10,
                 now: Optional[Callable[[], float]] = None,
                 sleep: Optional[Callable[[float], None]] = None) -> None:
        if qps <= 0:
            raise ValueError(f"qps must be positive, got {qps}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.qps = float(qps)
        self.burst = int(burst)
        self._now = now or time.monotonic
        self._sleep = sleep or time.sleep
        self._lock = threading.Lock()
        self._tokens = float(burst)  # may go negative: queued debt
        self._last = self._now()
        self._waited_total = 0.0

    def _refill(self, now: float) -> None:
        """Accrue tokens since the last accounting instant (lock held)."""
        elapsed = max(0.0, now - self._last)
        self._last = now
        self._tokens = min(float(self.burst),
                           self._tokens + elapsed * self.qps)

    def try_accept(self) -> bool:
        """Take a token if one is available right now; never blocks."""
        with self._lock:
            self._refill(self._now())
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def wait(self) -> float:
        """Reserve the next token, blocking until the reservation
        matures. Returns the seconds slept (0.0 when admitted
        immediately)."""
        with self._lock:
            now = self._now()
            self._refill(now)
            self._tokens -= 1.0
            delay = 0.0 if self._tokens >= 0.0 else -self._tokens / self.qps
            self._waited_total += delay
        if delay > 0.0:
            if delay > _LONG_THROTTLE_WARN_S:
                logger.warning(
                    "client-side throttling: waiting %.2fs for an API "
                    "token (qps=%g burst=%d)", delay, self.qps, self.burst)
            self._sleep(delay)
        return delay

    @property
    def waited_seconds_total(self) -> float:
        """Cumulative seconds callers spent throttled (observability)."""
        with self._lock:
            return self._waited_total


class Event:
    """A recorded Kubernetes-style event (type/reason/message on an object).

    ``count``/``first_seen``/``last_seen`` carry the duplicate-counting
    semantics of the v1 Events API (client-go bumps ``count`` on the
    existing event instead of creating a new one)."""

    NORMAL = "Normal"
    WARNING = "Warning"

    __slots__ = ("object_name", "kind", "type", "reason", "message",
                 "count", "first_seen", "last_seen", "__weakref__")

    def __init__(self, object_name: str, kind: str, type_: str, reason: str,
                 message: str, count: int = 1,
                 first_seen: float = 0.0, last_seen: float = 0.0) -> None:
        self.object_name = object_name
        self.kind = kind
        self.type = type_
        self.reason = reason
        self.message = message
        self.count = count
        self.first_seen = first_seen
        self.last_seen = last_seen

    def __repr__(self) -> str:
        suffix = f" x{self.count}" if self.count > 1 else ""
        return (f"Event({self.type} {self.reason} on {self.kind}/"
                f"{self.object_name}: {self.message}{suffix})")


class EventRecorder:
    """Collects events emitted on cluster objects.

    Equivalent of client-go's record.EventRecorder as used by the reference
    (util.go:141-153); the in-memory list doubles as the FakeRecorder used
    throughout the reference test suite (upgrade_suit_test.go:63).
    """

    def __init__(self, capacity: int = 1000) -> None:
        self._events: list[Event] = []
        self._lock = threading.Lock()
        self._capacity = capacity

    def event(self, obj: object, type_: str, reason: str, message: str) -> None:
        name = getattr(getattr(obj, "metadata", obj), "name", str(obj))
        kind = type(obj).__name__
        with self._lock:
            self._events.append(Event(name, kind, type_, reason, message))
            if len(self._events) > self._capacity:
                self._events.pop(0)

    @property
    def events(self) -> list[Event]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def find(self, reason: Optional[str] = None,
             type_: Optional[str] = None) -> list[Event]:
        with self._lock:
            return [e for e in self._events
                    if (reason is None or e.reason == reason)
                    and (type_ is None or e.type == type_)]


class CorrelatingEventRecorder(EventRecorder):
    """EventRecorder with client-go ``EventCorrelator`` semantics.

    The reference gets this from client-go's event broadcaster for
    free; without it, a 256-node drain wave would write an event per
    node transition straight to the apiserver. Three layers, applied in
    client-go's order:

    1. **Aggregation** (``EventAggregator``): more than
       ``max_similar`` events sharing (object, type, reason) inside
       ``similar_interval`` seconds fold into one
       "(combined from similar events)" event keyed without the
       message.
    2. **Duplicate counting** (``eventObserve``): an event identical to
       one already recorded bumps its ``count``/``last_seen`` in place —
       the v1 Events API PATCH path — instead of appending.
    3. **Spam filtering** (``EventSourceObjectSpamFilter``): a token
       bucket per involved object (burst ``spam_burst``, refill
       ``spam_qps``) drops floods that survive aggregation.

    Correlation state is LRU-bounded at ``lru_size`` keys (client-go
    bounds its aggregator/spam caches at 4096 the same way) so churning
    objects cannot grow the recorder without bound over an operator's
    lifetime.

    An optional ``sink`` callable receives every event that survives
    correlation — ``(key, event_snapshot, is_update)``, where ``key`` is
    the stable correlation identity and the snapshot is immutable — for
    forwarding to a real Events API. Deliveries are queued (bounded,
    overflow-dropping) and drained by one background writer thread, so
    emitting an event never blocks a reconcile on network I/O and
    cluster writes land in emission order (the client-go broadcaster's
    buffered-channel design). Tests call :meth:`flush` to join the
    queue. The in-memory list keeps serving either way.
    """

    def __init__(self, capacity: int = 1000,
                 clock: Optional[Clock] = None,
                 max_similar: int = 10,
                 similar_interval: float = 600.0,
                 spam_burst: int = 25,
                 spam_qps: float = 1.0 / 300.0,
                 lru_size: int = 4096,
                 sink: Optional[Callable[[tuple, Event, bool], None]] = None,
                 sink_queue_size: int = 512) -> None:
        super().__init__(capacity)
        self._clock = clock or Clock()
        self._max_similar = max_similar
        self._similar_interval = similar_interval
        self._spam_burst = spam_burst
        self._spam_qps = spam_qps
        self._lru_size = lru_size
        self._sink = sink
        self.sink_dropped_total = 0
        if sink is not None:
            import queue as _queue

            self._sink_queue: "_queue.Queue[Optional[tuple]]" = \
                _queue.Queue(maxsize=sink_queue_size)
            self._writer = threading.Thread(
                target=self._drain_sink, name="event-sink-writer",
                daemon=True)
            self._writer.start()
        # aggregation key -> (window start, events seen) — LRU-bounded
        self._similar: "OrderedDict[tuple, tuple[float, int]]" = \
            OrderedDict()
        # full key (incl. message) -> recorded Event for count bumping
        self._by_key: dict[tuple, Event] = {}
        # parallel to _events: the _by_key key of each recorded event,
        # so capacity eviction is an O(1) pop instead of a dict rebuild
        self._event_keys: list[tuple] = []
        # spam key (per object) -> token bucket — LRU-bounded
        self._buckets: "OrderedDict[tuple, TokenBucketRateLimiter]" = \
            OrderedDict()
        self.dropped_total = 0

    def _lru_touch(self, lru: "OrderedDict", key: tuple) -> None:
        """Mark ``key`` most-recently-used; evict the coldest past the
        bound (lock held)."""
        lru.move_to_end(key)
        while len(lru) > self._lru_size:
            lru.popitem(last=False)

    def _spam_ok(self, key: tuple) -> bool:
        bucket = self._buckets.get(key)
        if bucket is None:
            bucket = TokenBucketRateLimiter(
                qps=self._spam_qps, burst=self._spam_burst,
                now=self._clock.now)
            self._buckets[key] = bucket
        self._lru_touch(self._buckets, key)
        return bucket.try_accept()

    def event(self, obj: object, type_: str, reason: str,
              message: str) -> None:
        name = getattr(getattr(obj, "metadata", obj), "name", str(obj))
        kind = type(obj).__name__
        now = self._clock.now()
        with self._lock:
            agg_key = (kind, name, type_, reason)
            start, seen = self._similar.get(agg_key, (now, 0))
            if now - start > self._similar_interval:
                start, seen = now, 0  # window expired: reset
            seen += 1
            self._similar[agg_key] = (start, seen)
            self._lru_touch(self._similar, agg_key)
            if seen > self._max_similar:
                message = "(combined from similar events) " + message
                full_key = agg_key  # aggregate: message no longer keys
            else:
                full_key = agg_key + (message,)

            if not self._spam_ok((kind, name)):
                self.dropped_total += 1
                return

            existing = self._by_key.get(full_key)
            if existing is not None:
                existing.count += 1
                existing.last_seen = now
                existing.message = message
                event = existing
                is_update = True
            else:
                event = Event(name, kind, type_, reason, message,
                              count=1, first_seen=now, last_seen=now)
                self._by_key[full_key] = event
                self._events.append(event)
                self._event_keys.append(full_key)
                if len(self._events) > self._capacity:
                    self._events.pop(0)
                    self._by_key.pop(self._event_keys.pop(0), None)
                is_update = False
            if self._sink is not None:
                # snapshot under the lock: the live Event keeps mutating
                # (count bumps) and the writer thread must not read torn
                # field combinations
                snapshot = Event(event.object_name, event.kind,
                                 event.type, event.reason, event.message,
                                 count=event.count,
                                 first_seen=event.first_seen,
                                 last_seen=event.last_seen)
                try:
                    self._sink_queue.put_nowait(
                        (full_key, snapshot, is_update))
                except Exception:
                    # full queue: drop rather than block the emitter
                    # (client-go's broadcaster makes the same trade)
                    self.sink_dropped_total += 1

    def _drain_sink(self) -> None:
        while True:
            item = self._sink_queue.get()
            try:
                if item is None:
                    return
                try:
                    self._sink(*item)
                except Exception:
                    logger.exception("event sink delivery failed")
            finally:
                self._sink_queue.task_done()

    def flush(self) -> None:
        """Block until every queued sink delivery has been processed."""
        if self._sink is not None:
            self._sink_queue.join()

    def close(self) -> None:
        """Stop the sink writer thread (queued deliveries drain first)."""
        if self._sink is not None and self._writer.is_alive():
            self._sink_queue.put(None)
            self._writer.join(timeout=5.0)

    def clear(self) -> None:
        """Reset the recorder's IN-MEMORY state: recorded events,
        correlation/aggregation maps, and both drop counters. Sink
        deliveries already queued are NOT recalled — they were accepted
        before the clear and the cluster write completes asynchronously
        (call :meth:`flush` first to drain them deterministically)."""
        with self._lock:
            self._events.clear()
            self._event_keys.clear()
            self._by_key.clear()
            self._similar.clear()
            self._buckets.clear()
            self.dropped_total = 0
            self.sink_dropped_total = 0


def log_event(recorder: Optional[EventRecorder], obj: object, type_: str,
              reason: str, message: str) -> None:
    """Nil-safe event emission (reference logEvent/logEventf,
    util.go:141-153)."""
    if recorder is not None:
        recorder.event(obj, type_, reason, message)


class Worker:
    """Runs fire-and-forget node actions, sync or async.

    The reference spawns one detached goroutine per slow node action (drain:
    drain_manager.go:108-132, eviction: pod_manager.go:167-226).  Detached
    threads make tests and the simulator nondeterministic, so the executor is
    a seam: ``Worker(async_mode=False)`` runs actions inline (deterministic,
    used by tests/bench), ``async_mode=True`` spawns a daemon thread per
    action like the reference.  ``join()`` waits for in-flight actions.
    """

    def __init__(self, async_mode: bool = True) -> None:
        self.async_mode = async_mode
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()

    def submit(self, fn: Callable[[], None]) -> None:
        if not self.async_mode:
            fn()
            return
        thread = threading.Thread(target=fn, daemon=True)
        with self._lock:
            self._threads = [t for t in self._threads if t.is_alive()]
            self._threads.append(thread)
        thread.start()

    def join(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            remaining = deadline - time.monotonic()
            if remaining > 0:
                t.join(remaining)


def chunked(items: list, size: int) -> Iterator[list]:
    """Yield ``items`` in chunks of at most ``size``."""
    for i in range(0, len(items), size):
        yield items[i:i + size]
