"""OperatorManager: the controller-runtime "manager" analogue.

The reference's consumers get informer caches, a rate-limited work queue,
watch→reconcile wiring, leader election and a metrics endpoint for free
from ``ctrl.NewManager`` (SURVEY.md §1 L0/L5); this build owns each of
those pieces (:mod:`tpu_operator_libs.controller`,
:mod:`tpu_operator_libs.k8s.cached`,
:mod:`tpu_operator_libs.k8s.leaderelection`,
:mod:`tpu_operator_libs.metrics`) — this module packages them the same
way so a consumer operator is four lines:

.. code-block:: python

    mgr = OperatorManager(cluster, namespace="kube-system",
                          reconcile=my_reconcile)
    mgr.run(stop_event)          # blocks; Ctrl-C sets the event

With ``leader_election`` configured, caches and the reconcile loop start
only after the Lease is won (standby replicas hold no watches), and
losing leadership stops them — the HA replica pattern
controller-runtime's manager implements.
"""

from __future__ import annotations

import logging
import threading
from typing import TYPE_CHECKING, Callable, Optional

from tpu_operator_libs.controller import (
    CLUSTER_KEY,
    Controller,
    ExponentialBackoffRateLimiter,
    ReconcileResult,
)
from tpu_operator_libs.k8s.client import K8sClient

if TYPE_CHECKING:  # pragma: no cover - types only
    from tpu_operator_libs.k8s.leaderelection import (
        LeaderElectionConfig,
    )
    from tpu_operator_libs.k8s.sharding import (
        ShardElectionConfig,
        ShardElector,
    )
    from tpu_operator_libs.metrics import MetricsRegistry
    from tpu_operator_libs.upgrade.nudger import ReconcileNudger
    from tpu_operator_libs.util import Clock

logger = logging.getLogger(__name__)


class OperatorManager:
    """Wires cache + controller + optional leader election into one
    runnable.

    Parameters
    ----------
    client:
        The cluster backend (FakeCluster or RealCluster). When
        ``use_cache`` is true (default), reads go through a
        :class:`~tpu_operator_libs.k8s.cached.CachedReadClient` built at
        start time; access it via :attr:`client` from inside
        ``reconcile``.
    reconcile:
        ``fn(key) -> Optional[ReconcileResult]`` — the consumer's
        reconcile, called from worker threads exactly like
        :class:`~tpu_operator_libs.controller.Controller`'s.
    leader_election:
        Optional :class:`~tpu_operator_libs.k8s.leaderelection.
        LeaderElectionConfig`; when set, :meth:`run` contends for the
        Lease and gates the whole runtime on holding it.
    gc_freeze_after_sync:
        Freeze the CPython heap once the informer caches have synced
        (``gc.freeze()``), exempting the long-lived cache from every
        later generational GC scan. Recommended for fleets of
        thousands of nodes; off by default because frozen objects are
        never collected.
    """

    def __init__(self, client: K8sClient, namespace: str,
                 reconcile: Callable[[str], Optional[ReconcileResult]],
                 name: str = "operator",
                 use_cache: bool = True,
                 cache_sync_timeout: float = 60.0,
                 resync_period: Optional[float] = 300.0,
                 workers: int = 1,
                 leader_election: Optional[
                     "LeaderElectionConfig"] = None,
                 shard_election: Optional[
                     "ShardElectionConfig"] = None,
                 leader_election_clock: Optional["Clock"] = None,
                 metrics: Optional["MetricsRegistry"] = None,
                 rate_limiter: Optional[ExponentialBackoffRateLimiter] = None,
                 gc_freeze_after_sync: bool = False,
                 nudger: Optional["ReconcileNudger"] = None,
                 ) -> None:
        self._raw_client = client
        self._namespace = namespace
        self._reconcile = reconcile
        self._name = name
        self._use_cache = use_cache
        self._cache_sync_timeout = cache_sync_timeout
        self._resync_period = resync_period
        self._workers = workers
        self._leader_election = leader_election
        if leader_election is not None and shard_election is not None:
            raise ValueError(
                "leader_election and shard_election are exclusive: the "
                "sharded control plane replaces the single global lock")
        self._shard_election = shard_election
        #: The live ShardElector once run() starts in sharded mode —
        #: hand it to ClusterUpgradeStateManager.with_sharding (and the
        #: remediation machine's) so reconciles run ownership-filtered
        #: and fenced.
        self.shard_elector: Optional["ShardElector"] = None
        self._leader_election_clock = leader_election_clock
        self._metrics = metrics
        self._rate_limiter = rate_limiter
        self._gc_freeze_after_sync = gc_freeze_after_sync
        # Completion-wakeup seam: bound to the controller at start()
        # (nudge → enqueue now; deadline slots → WorkQueue.add_after),
        # unbound at stop(). Build one ReconcileNudger, hand it to the
        # state managers via with_nudger, and pass it here — async
        # outcomes then reconcile the moment they land instead of on
        # the resync poll.
        self.nudger = nudger

        self._cached = None
        self._controller: Optional[Controller] = None
        self._started = threading.Event()
        self._lock = threading.Lock()
        self._starting = False
        self._stop_requested = threading.Event()
        self._start_error: Optional[BaseException] = None

    # -- accessors --------------------------------------------------------
    @property
    def client(self) -> K8sClient:
        """The read client reconcilers should use: the informer cache
        once started (GetClient analogue), else the raw backend."""
        return self._cached if self._cached is not None else self._raw_client

    @property
    def is_started(self) -> bool:
        return self._started.is_set()

    def has_synced(self, timeout: Optional[float] = None) -> bool:
        """WaitForCacheSync analogue (always True without a cache)."""
        if self._cached is None:
            return True
        return self._cached.has_synced(timeout=timeout)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        """Build caches, sync them, and start the controller. Without
        leader election, call this directly; :meth:`run` calls it (on a
        worker thread) after winning the Lease. Raises if caches fail to
        sync. The cache-sync wait runs without holding the manager lock,
        so a concurrent :meth:`stop` returns promptly and aborts the
        sync."""
        with self._lock:
            if self._controller is not None or self._starting:
                raise RuntimeError("manager already started")
            self._starting = True
            # a fresh start supersedes any previous stop request; an
            # in-flight stop() from a previous life has already taken its
            # refs under this lock
            self._stop_requested.clear()
        cached = None
        try:
            if self._use_cache:
                from tpu_operator_libs.k8s.cached import CachedReadClient

                cached = CachedReadClient(self._raw_client, self._namespace)
                import time as _time

                end = _time.monotonic() + self._cache_sync_timeout
                synced = False
                # do-while shape: an already-synced cache must pass even
                # with cache_sync_timeout <= 0 (the deadline-first loop
                # would return a spurious TimeoutError without ever
                # asking).
                while True:
                    if self._stop_requested.is_set():
                        cached.stop()
                        return
                    remaining = end - _time.monotonic()
                    if cached.has_synced(
                            timeout=min(0.2, max(0.0, remaining))):
                        synced = True
                        break
                    if remaining <= 0:
                        break
                if not synced:
                    cached.stop()
                    raise TimeoutError(
                        f"informer caches failed to sync within "
                        f"{self._cache_sync_timeout}s")
            if self._gc_freeze_after_sync:
                # Large-fleet tuning: the freshly-synced informer cache
                # is effectively process-permanent, yet CPython's
                # generational GC rescans it on every collection the
                # reconcile loop's allocation traffic triggers — at 4096
                # nodes that was 40% of pass latency and scaled
                # superlinearly. freeze() moves the current heap to the
                # permanent generation (the standard large-heap CPython
                # mitigation); the cost is that objects alive right now
                # are never collected, bounded by one fleet snapshot.
                import gc

                gc.collect()
                gc.freeze()
            controller = Controller(
                self._reconcile, name=self._name,
                rate_limiter=self._rate_limiter,
                resync_period=self._resync_period,
                metrics=self._metrics)
            # Events trigger reconciles *after* they are applied to the
            # read cache: the controller is fed by the cache informers'
            # handlers (controller-runtime sources its workqueue the same
            # way), so a reconcile never races its own trigger reading a
            # pre-event cache. Without a cache, fall back to a raw watch.
            if cached is not None:
                cached.add_event_handler(
                    lambda *_a: controller.enqueue())
            else:
                controller.watch(
                    self._raw_client.watch(namespace=self._namespace))
            with self._lock:
                if self._stop_requested.is_set():
                    if cached is not None:
                        cached.stop()
                    return
                self._cached = cached
                self._controller = controller
                # Publish and start under ONE lock hold: a concurrent
                # stop() is thereby ordered strictly before the publish
                # (caught by the check above) or after the workers exist
                # (normal teardown) — there is no window where it stops
                # a not-yet-started controller. controller.start only
                # spawns threads, so holding the lock here is cheap; the
                # lock-free waiting the docstring describes is for the
                # long cache-sync loop above, not this.
                controller.start(workers=self._workers)
                self._started.set()
            if self.nudger is not None:
                self.nudger.bind(
                    wake=controller.enqueue,
                    schedule=lambda d: controller.queue.add_after(
                        CLUSTER_KEY, d))
            logger.info("%s: started (cache=%s)", self._name,
                        self._use_cache)
        except BaseException:
            if cached is not None and self._cached is None:
                cached.stop()
            raise
        finally:
            with self._lock:
                self._starting = False

    def stop(self, timeout: float = 10.0) -> None:
        self._stop_requested.set()
        if self.nudger is not None:
            self.nudger.unbind()
        with self._lock:
            controller, cached = self._controller, self._cached
            self._controller = None
            self._cached = None
            self._started.clear()
        if controller is not None:
            controller.stop(timeout=timeout)
        if cached is not None:
            cached.stop()
        logger.info("%s: stopped", self._name)

    def run(self, stop: Optional[threading.Event] = None) -> None:
        """Blocking entry point (manager.Start analogue).

        Without leader election: start, then wait for ``stop``. With it:
        contend for the Lease; the runtime starts on acquiring and stops
        on losing it, and the loop exits when ``stop`` is set (or
        leadership is lost — the standard exit-and-let-the-replica-
        controller-restart-us pattern)."""
        stop = stop or threading.Event()
        if self._shard_election is not None:
            self._run_sharded(stop)
            return
        if self._leader_election is None:
            self.start()
            try:
                stop.wait()
            finally:
                self.stop()
            return

        from tpu_operator_libs.k8s.leaderelection import LeaderElector

        def start_async():
            # a worker thread, NOT the elector's: the elector must keep
            # renewing the Lease while caches sync, or a slow sync blows
            # the renew deadline and a second leader starts writing node
            # state concurrently (split brain)
            try:
                self.start()
            except Exception as exc:  # noqa: BLE001 — surfaced via run()
                logger.exception("%s: start after winning lease failed",
                                 self._name)
                self._start_error = exc
                stop.set()

        def on_started():
            threading.Thread(target=start_async, daemon=True,
                             name=f"{self._name}-start").start()

        def on_stopped():
            self.stop()
            # deposed: exit so the replica controller restarts us as a
            # follower (controller-runtime does the same)
            stop.set()

        elector = LeaderElector(self._raw_client, self._leader_election,
                                clock=self._leader_election_clock,
                                on_started_leading=on_started,
                                on_stopped_leading=on_stopped)
        elector_thread = threading.Thread(
            target=lambda: elector.run(stop), daemon=True,
            name=f"{self._name}-elector")
        elector_thread.start()
        try:
            stop.wait()
        finally:
            elector.release()
            self.stop()
            elector_thread.join(timeout=5.0)
        if self._start_error is not None:
            # a startup failure must not look like a clean exit
            raise self._start_error

    def _run_sharded(self, stop: threading.Event) -> None:
        """Sharded-HA driver: contend for the member slot + per-shard
        Leases (k8s/sharding.py), start the runtime once ≥1 shard is
        owned, and keep electing while it runs. Unlike the single-lock
        mode, losing SOME shards does not stop the runtime — the
        ownership filter and the write fence shrink the partition
        instead (an empty partition reconciles nothing); the runtime
        stops when the caller sets ``stop``, releasing every Lease so
        successors take over immediately."""
        from tpu_operator_libs.k8s.sharding import ShardElector

        elector = ShardElector(self._raw_client, self._shard_election,
                               clock=self._leader_election_clock)
        self.shard_elector = elector
        started = threading.Event()

        def start_async() -> None:
            try:
                self.start()
            except Exception as exc:  # noqa: BLE001 — surfaced via run()
                logger.exception("%s: start after winning shards failed",
                                 self._name)
                self._start_error = exc
                stop.set()

        def drive() -> None:
            while not stop.is_set():
                delay = elector.run_step()
                if elector.owned_shards() and not started.is_set():
                    started.set()
                    threading.Thread(target=start_async, daemon=True,
                                     name=f"{self._name}-start").start()
                stop.wait(delay)

        elector_thread = threading.Thread(
            target=drive, daemon=True, name=f"{self._name}-shard-elector")
        elector_thread.start()
        try:
            stop.wait()
        finally:
            elector.release_all()
            self.stop()
            elector_thread.join(timeout=5.0)
        if self._start_error is not None:
            raise self._start_error
